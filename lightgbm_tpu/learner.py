"""Serial TPU tree learner — the jitted leaf-wise tree grower.

TPU-native re-architecture of the reference learners
(ref: src/treelearner/serial_tree_learner.cpp:183 Train,
src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:170). The
``num_leaves - 1`` best-first splits become a single ``lax.scan`` with
fixed trip count; all state (row->leaf map, histogram pool, per-leaf best
splits) has static shapes, so the whole tree grows inside one XLA program
with no host round-trips (the CUDA learner pays one readback per split).

Key correspondences:
  - histogram pool  ~ HistogramPool (serial_tree_learner.cpp:40)
  - smaller-child build + sibling subtraction ~ serial_tree_learner.cpp:373,582
  - per-leaf best-split arrays ~ best_split_per_leaf_
  - row_leaf vector ~ CUDADataPartition's cuda_data_index_to_leaf_index_

Memory stance on the pool: the reference bounds host RAM with an LRU
cache (histogram_pool_size) and recomputes evicted histograms. Static
XLA shapes preclude an LRU; the full [L, F, B, 3] pool is kept in HBM
(5.5 MB at Higgs shape, ~784 MB worst-case at 255 leaves x 1k features
x 256 bins — well inside a 16 GB chip, and EFB bundling shrinks F for
exactly the wide datasets that would push it). Only one grower's pool
is live at a time; the buffer is freed when its program ends.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .obs import health as obs_health
from .obs.metrics import global_metrics  # noqa: F401  (re-export compat)
from .ops import histogram as hist_ops
from .ops import partition as part_ops
from .ops import split as split_ops
from .ops.histogram import COUNT, GRAD, HESS
from .ops.split import (FeatureMeta, K_MIN_SCORE, SplitHyperParams, SplitInfo,
                        find_best_split, leaf_gain_given_output, leaf_output,
                        leaf_output_smooth)


class TreeArrays(NamedTuple):
    """One grown tree, flat arrays (device). L = num_leaves slots.

    Splits are recorded in creation order: split s creates internal node s;
    its left child keeps leaf id `split_leaf[s]`, its right child is the new
    leaf id ``s + 1`` (the reference uses the same numbering,
    ref: src/io/tree.cpp Tree::Split).
    """
    split_leaf: jax.Array          # [L-1] int32, -1 when unused
    split_feature: jax.Array       # [L-1] int32
    split_bin_threshold: jax.Array  # [L-1] int32
    split_default_left: jax.Array  # [L-1] bool
    split_gain: jax.Array          # [L-1] f32
    split_cat_mask: jax.Array      # [L-1, B] bool (bins going left, cat)
    internal_value: jax.Array      # [L-1] f32 (unshrunk output of split node)
    internal_weight: jax.Array     # [L-1] f32 (sum_hess)
    internal_count: jax.Array      # [L-1] f32
    leaf_value: jax.Array          # [L] f32 (unshrunk)
    leaf_weight: jax.Array         # [L] f32
    leaf_count: jax.Array          # [L] f32
    num_leaves: jax.Array          # scalar int32


class _LeafSplits(NamedTuple):
    """Per-leaf stats + stored best split (ref: leaf_splits.hpp:23 +
    best_split_per_leaf_ in serial_tree_learner.h). min/max_bound are the
    leaf's output bounds inherited from ancestor monotone splits
    (ref: monotone_constraints.hpp:466 BasicLeafConstraints entries)."""
    sum_grad: jax.Array   # [L]
    sum_hess: jax.Array   # [L]
    count: jax.Array      # [L]
    depth: jax.Array      # [L] int32
    output: jax.Array     # [L] (path-smoothed) leaf output
    gain: jax.Array       # [L]
    feature: jax.Array    # [L] int32
    threshold: jax.Array  # [L] int32
    default_left: jax.Array  # [L] bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    left_output: jax.Array   # [L] candidate left-child output
    right_output: jax.Array  # [L] candidate right-child output
    cat_mask: jax.Array      # [L, B] bool candidate categorical mask
    min_bound: jax.Array     # [L] monotone lower output bound
    max_bound: jax.Array     # [L] monotone upper output bound


class _GrowState(NamedTuple):
    row_leaf: jax.Array   # [N] int32
    pool: jax.Array       # [L, F, B, 3] histogram pool
    leaves: _LeafSplits
    used_features: Optional[jax.Array]  # [L, F] bool (interaction constraints)
    n_applied: jax.Array  # scalar int32: applied-split counter (leaf ids)
    # leaf feature-range boxes [L, F] int32 (pairwise monotone modes only)
    box_lo: Optional[jax.Array] = None
    box_hi: Optional[jax.Array] = None


def _store_split(leaves: _LeafSplits, idx, info: SplitInfo, depth, output,
                 sum_grad, sum_hess, count, min_bound, max_bound,
                 valid) -> _LeafSplits:
    """Write one leaf's stats + its best candidate split at slot `idx`."""
    def upd(arr, val):
        return arr.at[idx].set(jnp.where(valid, val, arr[idx]))
    return _LeafSplits(
        sum_grad=upd(leaves.sum_grad, sum_grad),
        sum_hess=upd(leaves.sum_hess, sum_hess),
        count=upd(leaves.count, count),
        depth=upd(leaves.depth, depth),
        output=upd(leaves.output, output),
        gain=upd(leaves.gain, info.gain),
        feature=upd(leaves.feature, info.feature),
        threshold=upd(leaves.threshold, info.threshold),
        default_left=upd(leaves.default_left, info.default_left),
        left_sum_grad=upd(leaves.left_sum_grad, info.left_sum_grad),
        left_sum_hess=upd(leaves.left_sum_hess, info.left_sum_hess),
        left_count=upd(leaves.left_count, info.left_count),
        left_output=upd(leaves.left_output, info.left_output),
        right_output=upd(leaves.right_output, info.right_output),
        cat_mask=upd(leaves.cat_mask, info.cat_mask),
        min_bound=upd(leaves.min_bound, min_bound),
        max_bound=upd(leaves.max_bound, max_bound),
    )


def _allowed_features(used_row: jax.Array, groups: jax.Array) -> jax.Array:
    """Features usable below a node given the features already used on its
    path (ref: col_sampler.hpp interaction-constraint filtering): the
    union of constraint groups that contain every used feature."""
    # group g qualifies iff used_row is a subset of groups[g]
    qualifies = ~jnp.any(used_row[None, :] & ~groups, axis=1)  # [G]
    return jnp.any(groups & qualifies[:, None], axis=0)  # [F]


def _rand_bins(key, meta: FeatureMeta):
    """Extra-trees: one uniform random threshold bin per feature in
    [0, num_bins-2] (ref: feature_histogram.hpp:205 rand.NextInt)."""
    u = jax.random.uniform(key, meta.num_bins.shape)
    return jnp.floor(u * jnp.maximum(meta.num_bins - 1, 1)).astype(jnp.int32)


def _bynode_mask(key, feature_mask, ff_bynode: float):
    """Per-node feature subsample FROM the node's allowed set
    (ref: col_sampler.hpp GetByNode samples ceil(fraction * valid_count)
    of the currently-valid features, so a constrained node always keeps
    at least one usable feature)."""
    f = feature_mask.shape[0]
    u = jax.random.uniform(key, (f,))
    u_masked = jnp.where(feature_mask, u, jnp.inf)  # disallowed sort last
    cnt = jnp.sum(feature_mask).astype(jnp.float32)
    k = jnp.maximum(jnp.ceil(ff_bynode * cnt), 1.0).astype(jnp.int32)
    thr = jnp.sort(u_masked)[jnp.clip(k - 1, 0, f - 1)]
    return feature_mask & (u_masked <= thr)


def _node_randomness(node_key, salt, meta, feature_mask,
                     extra_trees: bool, ff_bynode: float):
    """(rand_bins, node feature mask) for one candidate evaluation."""
    if node_key is None:
        return None, feature_mask
    key = jax.random.fold_in(node_key, salt)
    rb = _rand_bins(jax.random.fold_in(key, 0), meta) if extra_trees else None
    fm = _bynode_mask(jax.random.fold_in(key, 1), feature_mask,
                      ff_bynode) if ff_bynode < 1.0 else feature_mask
    return rb, fm


def _pad_rows(arrays, axes, n: int, mult: int, pad_values):
    """Pad each array's row axis (given per-array in `axes`) so the row
    count divides `mult` — shard_map needs equal per-device slices."""
    pad = (-n) % mult
    if pad == 0:
        return arrays
    out = []
    for a, ax, v in zip(arrays, axes, pad_values):
        cfg = [(0, 0)] * a.ndim
        cfg[ax] = (0, pad)
        out.append(jnp.pad(a, cfg, constant_values=v))
    return out


def _sharded_pallas_build(shard_mesh, *, max_bins: int, dtype,
                          row_chunk: int, precision: str,
                          impl: str = "pallas",
                          hist_reduce: str = "psum",
                          deterministic: bool = False):
    """Single-leaf histogram build distributed over the mesh row axis:
    each shard runs the histogram kernel on its rows, results reduce —
    the shard_map analog of HistogramSumReducer + Allreduce
    (ref: data_parallel_tree_learner.cpp:287-297).

    hist_reduce="scatter" replaces the full-histogram psum with a
    ``psum_scatter`` over the (zero-padded) feature axis: each shard
    receives only its owned 1/W feature slice — the reference's
    ReduceScatter — and the result stays feature-sharded for the
    scatter split stage (parallel/scatter.py). Bitwise: psum_scatter
    slices equal the matching psum rows, so models are unchanged.

    On a hierarchical ("dcn", "ici") mesh, rows shard over BOTH axes,
    the scatter runs over the fast in-process ICI axis and the owned
    slice then psums over the slow DCN link — only 1/W_ici of the
    histogram ever crosses DCN (int32 on the quantized path, so the
    compressed partial sums stay exact)."""
    from jax.sharding import PartitionSpec as P
    axes = tuple(shard_mesh.axis_names)
    row_axes = axes if len(axes) > 1 else axes[0]
    scat_axis = axes[-1]
    width = int(shard_mesh.shape[scat_axis])
    scatter = hist_reduce == "scatter"

    def local(b_l, g_l, h_l, m_l):
        hl = hist_ops.build_histogram(
            b_l, g_l, h_l, m_l, max_bins=max_bins, dtype=dtype,
            row_chunk=row_chunk, impl=impl, precision=precision,
            deterministic=deterministic)
        # tagged health wrapper: trace-time counters + runtime per-call
        # attribution through the enclosing program's manifest
        if not scatter:
            return obs_health.psum(hl, row_axes, tag="hist/psum")
        fpad = (-hl.shape[0]) % width
        if fpad:
            hl = jnp.pad(hl, ((0, fpad), (0, 0), (0, 0)))
        hl = obs_health.psum_scatter(hl, scat_axis,
                                     tag="hist/psum_scatter",
                                     scatter_dimension=0)
        if len(axes) > 1:
            hl = obs_health.psum(hl, axes[:-1], tag="hist/psum_dcn")
        return hl

    from .parallel.mesh import shard_map as _shard_map
    fn = _shard_map(local, mesh=shard_mesh,
                    in_specs=(P(None, row_axes), P(row_axes), P(row_axes),
                              P(row_axes)),
                    out_specs=(P(scat_axis, None, None) if scatter
                               else P()))

    def build(bins, g, h, m):
        # padded rows carry mask 0 -> no histogram contribution
        bins, g, h, m = _pad_rows((bins, g, h, m), (1, 0, 0, 0),
                                  bins.shape[1], shard_mesh.size,
                                  (0, 0.0, 0.0, 0.0))
        return fn(bins, g, h, m)
    return build


def _sharded_pallas_multi(shard_mesh, *, max_bins: int,
                          precision: str, int8: bool,
                          impl: str = "pallas",
                          hist_reduce: str = "psum",
                          deterministic: bool = False):
    """Multi-leaf wave histogram pass distributed over the mesh row axis.

    int8=True: the int8 x int8 -> int32 kernel (MXU pallas where Mosaic
    runs, its exact-integer XLA twin for impl="xla") runs per shard and
    the reduce moves INT32 histograms — exact integer accumulation
    across the mesh, the collective analog of the reference's quantized
    histogram reduction (ref: data_parallel_tree_learner.cpp:290-297,
    which reduces packed integer bins instead of floats). Callers
    dequantize AFTER the reduce, so cross-shard sums are exact
    multiples of the grad/hess scales.

    hist_reduce="scatter": ``psum_scatter`` over the (zero-padded)
    feature axis instead of the full psum — each shard receives only
    its owned feature slice (ReduceScatter,
    data_parallel_tree_learner.cpp:287) and the result stays
    feature-sharded for the scatter split stage. Hierarchical
    ("dcn", "ici") meshes scatter over ICI and psum the owned slice
    over DCN (see _sharded_pallas_build).
    """
    from jax.sharding import PartitionSpec as P
    from .ops.pallas_histogram import (hist_pallas_multi,
                                       hist_pallas_multi_int8,
                                       hist_multi, hist_multi_int8)
    axes = tuple(shard_mesh.axis_names)
    row_axes = axes if len(axes) > 1 else axes[0]
    scat_axis = axes[-1]
    width = int(shard_mesh.shape[scat_axis])
    scatter = hist_reduce == "scatter"

    def local(b_l, ghT_l, rl_l, ids):
        if impl == "pallas":
            if int8:
                h = hist_pallas_multi_int8(b_l, ghT_l, rl_l, ids,
                                           max_bins=max_bins,
                                           num_slots=ids.shape[0])
            else:
                h = hist_pallas_multi(b_l, ghT_l, rl_l, ids,
                                      max_bins=max_bins,
                                      num_slots=ids.shape[0],
                                      precise=precision)
        elif int8:
            # per-shard exact-integer XLA twin of the MXU kernel
            h = hist_multi_int8(b_l, ghT_l, rl_l, ids, max_bins=max_bins,
                                num_slots=ids.shape[0], impl=impl)
        else:
            h = hist_multi(b_l, ghT_l, rl_l, ids, max_bins=max_bins,
                           num_slots=ids.shape[0], impl=impl,
                           precision=precision,
                           deterministic=deterministic)
        if not scatter:
            return obs_health.psum(h, row_axes, tag="hist/psum_wave")
        fpad = (-h.shape[1]) % width
        if fpad:
            h = jnp.pad(h, ((0, 0), (0, fpad), (0, 0), (0, 0)))
        # ReduceScatter over the feature axis: INT32 payloads on the
        # int8 path stay exact under any reduction grouping
        h = obs_health.psum_scatter(h, scat_axis,
                                    tag="hist/psum_scatter",
                                    scatter_dimension=1)
        if len(axes) > 1:
            h = obs_health.psum(h, axes[:-1], tag="hist/psum_dcn")
        return h

    from .parallel.mesh import shard_map as _shard_map
    fn = _shard_map(local, mesh=shard_mesh,
                    in_specs=(P(None, row_axes), P(row_axes, None),
                              P(row_axes), P()),
                    out_specs=(P(None, scat_axis, None, None) if scatter
                               else P()))

    def multi(bins, ghT, row_leaf, ids):
        # padded rows: leaf id -1 matches no slot (slots are >= 0 or the
        # invalid sentinel -2), gh rows are zero
        bins, ghT, row_leaf = _pad_rows((bins, ghT, row_leaf), (1, 0, 0),
                                        bins.shape[1], shard_mesh.size,
                                        (0, 0, -1))
        return fn(bins, ghT, row_leaf, ids)
    return multi


def grow_tree(bins_fm: jax.Array,
              grad: jax.Array,
              hess: jax.Array,
              sample_mask: jax.Array,
              feature_mask: jax.Array,
              meta: FeatureMeta,
              hp: SplitHyperParams,
              max_depth: jax.Array,
              forced: Optional[tuple] = None,
              node_key: Optional[jax.Array] = None,
              *,
              num_leaves: int,
              max_bins: int,
              hist_dtype=jnp.float32,
              row_chunk: int = 0,
              hist_impl: str = "xla",
              hist_precision: str = "highest",
              interaction_groups=None,
              has_categorical: bool = True,
              extra_trees: bool = False,
              ff_bynode: float = 1.0,
              bundle=None,
              num_bundle_bins: int = 0,
              mono_pairwise: bool = False,
              shard_mesh=None,
              hist_reduce: str = "psum",
              sparse_shape=None,
              hist_deterministic: bool = False):
    """Grow one leaf-wise tree. Returns (TreeArrays, row_leaf [N] int32).

    sparse_shape: static (num_features, num_data) when bins_fm is a
    SparseBins COO pytree (ultra-sparse storage — see
    partition.SparseBins); histogram builds then run O(nnz)
    segment-sums instead of dense one-hot contractions.

    shard_mesh: a 1-D jax.sharding.Mesh with rows sharded over its axis.
    With hist_impl="pallas", histogram builds run per-shard inside
    shard_map (pallas_call does not auto-partition under GSPMD) and are
    psum-reduced — the device analog of HistogramSumReducer
    (ref: data_parallel_tree_learner.cpp:287-297).

    hist_reduce: "psum" all-reduces full histograms (the A/B oracle);
    "scatter" reduce-scatters them over a static feature partition —
    each shard owns 1/W of the (zero-padded) feature axis, best-split
    search runs feature-sharded (parallel/scatter.py keeps it at the
    oracle's tensor shape for bit-parity) and per-shard winners combine
    through one tiny SplitInfo all_gather + argmax
    (ref: data_parallel_tree_learner.cpp:287-297 ReduceScatter +
    FindBestSplitsFromHistograms + SyncUpGlobalBestSplit). Demoted to
    psum when there is no multi-device mesh or the storage is
    EFB-bundled / COO-sparse (those builds don't run under shard_map).

    mono_pairwise: use the exact pairwise leaf-box monotone bounds
    (monotone_constraints_method intermediate/advanced — see
    split_ops.compute_box_bounds) instead of basic midpoint propagation.

    sample_mask: [N] float {0,1} bagging/GOSS selection (excluded rows still
    get a leaf assignment for score updates, but contribute no statistics —
    ref: bagging keeps full score updates, gbdt.cpp:502).
    forced: optional (leaf [L-1], feature [L-1], threshold_bin [L-1],
    is_categorical [L-1] bool) arrays; leaf entries >= 0 force that split
    at that scan step — numerical splits on bin <= threshold, categorical
    as the one-vs-rest bitset on the threshold's bin
    (ref: serial_tree_learner.cpp:628 ForceSplits).
    interaction_groups: optional [G, F] bool array of allowed feature
    combinations (ref: config.h interaction_constraints).
    """
    if sparse_shape is not None:
        num_features, num_data = sparse_shape
    else:
        num_data = bins_fm.shape[1]
        num_features = (bins_fm.shape[0] if bundle is None
                        else bundle[0].shape[0])
    L = num_leaves
    f32 = hist_dtype

    use_mesh = shard_mesh is not None and shard_mesh.size > 1
    if (not use_mesh or bundle is not None or sparse_shape is not None):
        hist_reduce = "psum"

    build_bins = max_bins if bundle is None else num_bundle_bins
    if sparse_shape is not None:
        assert bundle is None, "sparse COO storage is not bundled"
        build = functools.partial(
            hist_ops.build_histogram_sparse,
            num_features=num_features, max_bins=max_bins, dtype=f32)
    elif use_mesh and (hist_impl == "pallas" or hist_reduce == "scatter"):
        raw_build = _sharded_pallas_build(
            shard_mesh, max_bins=build_bins, dtype=f32,
            row_chunk=row_chunk, precision=hist_precision,
            impl=hist_impl, hist_reduce=hist_reduce,
            deterministic=hist_deterministic)
    else:
        raw_build = functools.partial(
            hist_ops.build_histogram, max_bins=build_bins, dtype=f32,
            row_chunk=row_chunk, impl=hist_impl, precision=hist_precision,
            deterministic=hist_deterministic)
    if sparse_shape is not None:
        pass  # build already set
    elif bundle is None:
        build = raw_build
    else:
        # EFB: build on the bundled [G, N] columns, expand to the logical
        # per-feature layout (ref: dataset.cpp:251 FastFeatureBundling)
        from .bundling import expand_bundle_hist
        group_of, offset_of, nb_arr = bundle

        def build(bins, grad_, hess_, mask_):
            hg = raw_build(bins, grad_, hess_, mask_)  # [G, B_tot, 3]
            totals = jnp.sum(hg[0], axis=0)  # every row hits group 0 once
            return expand_bundle_hist(hg, group_of, offset_of, nb_arr,
                                      max_bins, totals)

    if interaction_groups is not None:
        interaction_groups = jnp.asarray(interaction_groups, bool)
        root_allowed = jnp.any(interaction_groups, axis=0)
    else:
        root_allowed = None

    # --- root (ref: serial_tree_learner.cpp BeforeTrain root LeafSplits init)
    root_hist = build(bins_fm, grad, hess, sample_mask)
    root_g = jnp.sum(grad * sample_mask, dtype=f32)
    root_h = jnp.sum(hess * sample_mask, dtype=f32)
    root_c = jnp.sum(sample_mask, dtype=f32)
    root_out = leaf_output(root_g, root_h, hp)
    root_fmask = feature_mask if root_allowed is None else \
        feature_mask & root_allowed
    neg_inf, pos_inf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)

    if hist_reduce == "scatter":
        # feature-sharded split search + SplitInfo winner all_gather —
        # root gathers once, the scan-body sites gather L-1 times each
        from .parallel.scatter import make_scatter_split
        _scat_kw = dict(num_features=num_features,
                        hist_features=root_hist.shape[0],
                        has_categorical=has_categorical, batched=False)
        split_root_fn = make_scatter_split(shard_mesh, loop_factor=1,
                                           **_scat_kw)
        split_step_fn = make_scatter_split(shard_mesh,
                                           loop_factor=max(L - 1, 1),
                                           **_scat_kw)
    else:
        def _split_plain(hist, pg, ph, pc, meta_, hp_, fm, parent_out,
                         min_b, max_b, depth, rand_bins=None):
            return find_best_split(hist, pg, ph, pc, meta_, hp_, fm,
                                   parent_out, min_b, max_b, depth,
                                   has_categorical, rand_bins)
        split_root_fn = split_step_fn = _split_plain

    rb_root, fm_root = _node_randomness(node_key, 0, meta, root_fmask,
                                        extra_trees, ff_bynode)
    root_split = split_root_fn(root_hist, root_g, root_h, root_c,
                               meta, hp, fm_root, root_out,
                               neg_inf, pos_inf, jnp.int32(0), rb_root)

    zero_l = jnp.zeros((L,), f32)
    leaves = _LeafSplits(
        sum_grad=zero_l, sum_hess=zero_l, count=zero_l,
        depth=jnp.zeros((L,), jnp.int32),
        output=zero_l,
        gain=jnp.full((L,), K_MIN_SCORE, f32),
        feature=jnp.zeros((L,), jnp.int32),
        threshold=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), jnp.bool_),
        left_sum_grad=zero_l, left_sum_hess=zero_l, left_count=zero_l,
        left_output=zero_l, right_output=zero_l,
        cat_mask=jnp.zeros((L, max_bins), jnp.bool_),
        min_bound=jnp.full((L,), -jnp.inf, f32),
        max_bound=jnp.full((L,), jnp.inf, f32),
    )
    leaves = _store_split(leaves, 0, root_split, jnp.int32(1), root_out,
                          root_g, root_h, root_c, neg_inf, pos_inf, True)

    # pool shape follows the built histogram: [F, B, 3] replicated, or
    # the zero-padded [Fp, B, 3] feature-sharded slab in scatter mode
    # (GSPMD propagates the feature sharding through the pool updates)
    pool = jnp.zeros((L,) + tuple(root_hist.shape), f32)
    pool = pool.at[0].set(root_hist)

    state = _GrowState(
        row_leaf=jnp.zeros((num_data,), jnp.int32),
        pool=pool,
        leaves=leaves,
        used_features=(jnp.zeros((L, num_features), bool)
                       if interaction_groups is not None else None),
        n_applied=jnp.int32(0),
        box_lo=(jnp.zeros((L, num_features), jnp.int32)
                if mono_pairwise else None),
        box_hi=(jnp.full((L, num_features), max_bins - 1, jnp.int32)
                if mono_pairwise else None),
    )

    if forced is None:
        neg1 = jnp.full((L - 1,), -1, jnp.int32)
        forced = (neg1, neg1, neg1, jnp.zeros((L - 1,), jnp.bool_))
    forced_leaf_arr, forced_feat_arr, forced_thr_arr, forced_cat_arr = forced

    def step(state: _GrowState, step_idx):
        leaves = state.leaves

        # --- forced candidate (ref: serial_tree_learner.cpp:628
        # ForceSplits): stats gathered from the target leaf's histogram;
        # aborted (falling back to the best split) when degenerate or
        # loss-increasing, like the reference's abort_last_forced_split
        f_leaf = jnp.maximum(forced_leaf_arr[step_idx], 0)
        f_feat = jnp.maximum(forced_feat_arr[step_idx], 0)
        f_thr = forced_thr_arr[step_idx]
        f_is_cat = forced_cat_arr[step_idx]
        f_hist = state.pool[f_leaf]
        # numerical: cumulative bins <= threshold go left; categorical:
        # one-vs-rest on the forced category's bin (ref:
        # feature_histogram.hpp GatherInfoForThreshold{Numerical,
        # Categorical} — the reference's forced categorical split is the
        # single-category bitset, tree.h:375)
        bin_eq = (jnp.arange(f_hist.shape[1]) == f_thr)
        bin_sel = jnp.where(f_is_cat, bin_eq,
                            jnp.arange(f_hist.shape[1]) <= f_thr)
        f_left = jnp.sum(f_hist[f_feat] * bin_sel[:, None], axis=0)
        f_pg, f_ph, f_pc = (leaves.sum_grad[f_leaf], leaves.sum_hess[f_leaf],
                            leaves.count[f_leaf])
        f_lg, f_lh, f_lc = f_left[GRAD], f_left[HESS], f_left[COUNT]
        f_rg, f_rh, f_rc = f_pg - f_lg, f_ph - f_lh, f_pc - f_lc
        f_parent_out = leaves.output[f_leaf]
        f_out_l = leaf_output_smooth(f_lg, f_lh, f_lc, f_parent_out, hp)
        f_out_r = leaf_output_smooth(f_rg, f_rh, f_rc, f_parent_out, hp)
        f_gain = (leaf_gain_given_output(f_lg, f_lh, f_out_l, hp)
                  + leaf_gain_given_output(f_rg, f_rh, f_out_r, hp)
                  - leaf_gain_given_output(f_pg, f_ph, f_parent_out, hp))
        use_forced = (forced_leaf_arr[step_idx] >= 0) & (f_lc > 0) & \
            (f_rc > 0) & (f_gain > 0)

        best_leaf = jnp.where(use_forced, f_leaf,
                              jnp.argmax(leaves.gain).astype(jnp.int32))
        feat = jnp.where(use_forced, f_feat, leaves.feature[best_leaf])
        thr = jnp.where(use_forced, f_thr, leaves.threshold[best_leaf])
        # forced splits route missing by the zero-bin rule (categorical
        # partitioning ignores default_left: membership in cat_mask decides)
        forced_dleft = (~f_is_cat) & \
            (meta.missing_type[feat] == split_ops.MISSING_ZERO) & \
            (meta.default_bin[feat] <= thr)
        dleft = jnp.where(use_forced, forced_dleft,
                          leaves.default_left[best_leaf])
        forced_cat_mask = bin_eq[:leaves.cat_mask.shape[1]] & f_is_cat
        cat_mask = jnp.where(use_forced, forced_cat_mask,
                             leaves.cat_mask[best_leaf])

        # --- children stats: stored candidate, or the forced gather
        pg, ph, pc = (leaves.sum_grad[best_leaf], leaves.sum_hess[best_leaf],
                      leaves.count[best_leaf])
        lg = jnp.where(use_forced, f_lg, leaves.left_sum_grad[best_leaf])
        lh = jnp.where(use_forced, f_lh, leaves.left_sum_hess[best_leaf])
        lc = jnp.where(use_forced, f_lc, leaves.left_count[best_leaf])
        rg, rh, rc = pg - lg, ph - lh, pc - lc

        valid = use_forced | (leaves.gain[best_leaf] > 0.0)
        # applied-split counter ids: a forced split can revive growth
        # after an invalid step, so step_idx+1 would leave id gaps that
        # Tree.from_arrays/replay can't index. Invalid steps write to the
        # out-of-bounds dummy L (scatter-dropped under jit).
        new_leaf = jnp.where(valid, state.n_applied + 1, L).astype(jnp.int32)
        n_applied = state.n_applied + valid.astype(jnp.int32)

        # --- partition rows (left keeps best_leaf id, right -> new_leaf)
        row_leaf = part_ops.apply_split(
            state.row_leaf, bins_fm, best_leaf, new_leaf, feat, thr, dleft,
            cat_mask, meta.num_bins, meta.missing_type, meta.is_categorical,
            valid, bundle)

        # --- histograms: build smaller child, subtract for the sibling
        # (ref: serial_tree_learner.cpp:373-386,582)
        left_smaller = lc <= rc
        small_id = jnp.where(left_smaller, best_leaf, new_leaf)
        small_mask = sample_mask * (row_leaf == small_id) * valid
        small_hist = build(bins_fm, grad, hess, small_mask)
        parent_hist = state.pool[best_leaf]
        large_hist = hist_ops.subtract_histogram(parent_hist, small_hist)
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)

        pool = state.pool
        pool = pool.at[best_leaf].set(jnp.where(valid, left_hist, parent_hist))
        pool = pool.at[new_leaf].set(
            jnp.where(valid, right_hist, pool[new_leaf]))

        # --- child outputs: the stored candidate's (clamped, with the
        # categorical l2 where applicable), or recomputed for forced splits
        parent_out = leaves.output[best_leaf]
        p_minb = leaves.min_bound[best_leaf]
        p_maxb = leaves.max_bound[best_leaf]
        f_out_l_c = jnp.clip(f_out_l, p_minb, p_maxb)
        f_out_r_c = jnp.clip(f_out_r, p_minb, p_maxb)
        out_l = jnp.where(use_forced, f_out_l_c,
                          leaves.left_output[best_leaf])
        out_r = jnp.where(use_forced, f_out_r_c,
                          leaves.right_output[best_leaf])
        if mono_pairwise:
            # pairwise modes tighten bounds after OTHER leaves split, so
            # stored candidate outputs must be re-clipped to the leaf's
            # CURRENT bounds (the reference instead recomputes affected
            # leaves' best splits, hpp:52 RecomputeConstraintsIfNeeded)
            out_l = jnp.clip(out_l, p_minb, p_maxb)
            out_r = jnp.clip(out_r, p_minb, p_maxb)
            box_lo, box_hi = split_ops.split_child_boxes(
                state.box_lo, state.box_hi, best_leaf, new_leaf, feat, thr,
                meta.is_categorical[feat], valid)
            out_now = leaves.output.at[best_leaf].set(
                jnp.where(valid, out_l, parent_out))
            out_now = out_now.at[new_leaf].set(
                jnp.where(valid, out_r, out_now[jnp.minimum(new_leaf, L - 1)]))
            leaf_in_use = jnp.arange(L, dtype=jnp.int32) <= n_applied
            minb_all, maxb_all = split_ops.compute_box_bounds(
                box_lo, box_hi, out_now, leaf_in_use, meta.monotone)
            leaves = leaves._replace(
                min_bound=jnp.where(valid, minb_all, leaves.min_bound),
                max_bound=jnp.where(valid, maxb_all, leaves.max_bound))
            l_min, l_max = minb_all[best_leaf], maxb_all[best_leaf]
            ni = jnp.minimum(new_leaf, L - 1)
            r_min, r_max = minb_all[ni], maxb_all[ni]
        else:
            box_lo, box_hi = state.box_lo, state.box_hi
            l_min, l_max, r_min, r_max = split_ops.propagate_monotone_bounds(
                out_l, out_r, meta.monotone[feat].astype(jnp.int32),
                meta.is_categorical[feat], p_minb, p_maxb)

        # --- per-child allowed features (interaction constraints)
        used_features = state.used_features
        if used_features is not None:
            child_used = used_features[best_leaf].at[feat].set(True)
            used_features = used_features.at[best_leaf].set(
                jnp.where(valid, child_used, used_features[best_leaf]))
            used_features = used_features.at[new_leaf].set(
                jnp.where(valid, child_used, used_features[new_leaf]))
            child_fmask = feature_mask & _allowed_features(
                child_used, interaction_groups)
        else:
            child_fmask = feature_mask

        # --- find child best splits
        child_depth = leaves.depth[best_leaf] + 1
        pen_depth = child_depth - 1  # reference depth of the child leaf
        rb_l, fm_l = _node_randomness(node_key, 2 * step_idx + 2, meta,
                                      child_fmask, extra_trees, ff_bynode)
        rb_r, fm_r = _node_randomness(node_key, 2 * step_idx + 3, meta,
                                      child_fmask, extra_trees, ff_bynode)
        split_l = split_step_fn(left_hist, lg, lh, lc, meta, hp,
                                fm_l, out_l, l_min, l_max,
                                pen_depth, rb_l)
        split_r = split_step_fn(right_hist, rg, rh, rc, meta, hp,
                                fm_r, out_r, r_min, r_max,
                                pen_depth, rb_r)
        # depth cap (ref: serial_tree_learner.cpp max_depth check)
        depth_ok = (max_depth <= 0) | (child_depth < max_depth)
        split_l = split_l._replace(
            gain=jnp.where(depth_ok, split_l.gain, K_MIN_SCORE))
        split_r = split_r._replace(
            gain=jnp.where(depth_ok, split_r.gain, K_MIN_SCORE))

        # the parent's chosen gain, before leaves is overwritten (for a
        # forced split: the actual gain of the forced threshold)
        chosen_gain = jnp.where(use_forced, f_gain, leaves.gain[best_leaf])

        leaves = _store_split(leaves, best_leaf, split_l, child_depth, out_l,
                              lg, lh, lc, l_min, l_max, valid)
        leaves = _store_split(leaves, new_leaf, split_r, child_depth, out_r,
                              rg, rh, rc, r_min, r_max, valid)

        record = dict(
            split_leaf=jnp.where(valid, best_leaf, -1),
            split_feature=feat,
            split_bin_threshold=thr,
            split_default_left=dleft,
            split_gain=jnp.where(valid, chosen_gain, 0.0),
            split_cat_mask=cat_mask,
            internal_value=parent_out,
            internal_weight=ph,
            internal_count=pc,
        )
        return (_GrowState(row_leaf, pool, leaves, used_features, n_applied,
                           box_lo, box_hi),
                dict(record=record, valid=valid))

    # unroll=2: a single-step scan body wrapping pallas_call lowers to a
    # pathologically slow while-loop on TPU (~1000x); any unrolling avoids it
    state, ys = lax.scan(step, state, jnp.arange(L - 1, dtype=jnp.int32),
                         unroll=2 if L > 2 else 1)
    records = ys["record"]
    # compact valid records first (a forced split can revive growth after
    # an invalid step; split s must create leaf s+1 gap-free)
    steps = jnp.arange(L - 1, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(ys["valid"], steps, steps + L))
    records = jax.tree_util.tree_map(lambda a: a[order], records)

    leaves = state.leaves
    leaf_values = leaves.output
    num_leaves_out = 1 + state.n_applied

    tree_arrays = TreeArrays(
        split_leaf=records["split_leaf"],
        split_feature=records["split_feature"],
        split_bin_threshold=records["split_bin_threshold"],
        split_default_left=records["split_default_left"],
        split_gain=records["split_gain"],
        split_cat_mask=records["split_cat_mask"],
        internal_value=records["internal_value"],
        internal_weight=records["internal_weight"],
        internal_count=records["internal_count"],
        leaf_value=leaf_values,
        leaf_weight=leaves.sum_hess,
        leaf_count=leaves.count,
        num_leaves=num_leaves_out,
    )
    return tree_arrays, state.row_leaf


# multi-leaf histogram kernel slot count: 128 MXU lanes // 3 channels.
# Shared by the wave scheduler, the traffic model, and the peak-memory
# model (obs/memory.py) — the wave slab is [HIST_SLOTS, F, B, 3].
HIST_SLOTS = 42


def _wave_schedule(num_leaves: int, wave_max: int, slots: int,
                   slots_per_split: int = 1):
    """Static split-batch sizes: 1, 2, 4, ... doubling, capped at
    min(max(8, splits_done // 2), wave_max, slots // slots_per_split),
    summing to num_leaves - 1.

    The frontier-proportional cap (a wave never splits more than ~half
    the leaves the tree currently has) keeps the split ORDER close to
    exact leaf-wise where it matters: early high-impact splits are
    near-exact, late waves batch up to the slot cap per histogram
    pass. Measured on held-out data this matches the exact grower's
    quality (AUC +-0.002 at 63 and 255 leaves) while cutting full-data
    histogram passes from num_leaves-1 to ~13 at 255 leaves; fixed caps
    either lose quality (32: -0.01 AUC) or passes (8: 34).

    slots_per_split makes the schedule SUBTRACTION-AWARE: with sibling
    subtraction each split consumes ONE of the multi-kernel's 42 slots
    (build the smaller child, derive the larger from the parent), so a
    wave packs up to 42 splits per full-data pass; without it (the
    oracle mode `tpu_wave_subtract=False`) every split needs TWO slots
    and late waves halve — 17 passes instead of 13 at 255 leaves, and
    every wave scans the rows of both children instead of only the
    smaller one (<= half a skewed split's rows). The A/B is what the
    obs `hist_traffic` counters and bench.py's JSON line report."""
    sizes, total, w = [], num_leaves - 1, 1
    done = 0
    while total > 0:
        cap = min(max(8, done // 2), max(wave_max, 1),
                  max(slots // slots_per_split, 1))
        s = min(w, total, cap)
        sizes.append(s)
        total -= s
        done += s
        w *= 2
    return sizes


def hist_traffic_model(*, num_data: int, storage_features: int,
                       max_bins: int, num_leaves: int, wave_max: int,
                       slots: int = HIST_SLOTS, pack_vpb=None,
                       gh_read_bytes: int = 12, row_leaf_bytes: int = 4,
                       subtract: bool = True, fused_grad: bool = False,
                       waved: bool = True):
    """Static per-iteration HBM traffic model of the histogram passes —
    the driver-visible counter behind ROADMAP item 3 (the shapes, wave
    schedule, packing factor and gh encoding are all trace-time
    constants, so the model is exact for what the compiled program
    streams; only gather inefficiency is outside it).

    Per pass: the bin tensor read (``storage_features x ceil(N/vpb)``
    bytes — halved by 4-bit packing), the gh operand read
    (12 B/row f32 ghT, 3 B/row int8 quantized, 12 B/row
    score+label+mask when the gradient pass is fused in-kernel) and the
    row->leaf read. ``fused_grad`` additionally drops the standalone
    gradient/bagging element-wise pass (read score/label/mask + write
    ghT ~= 24 B/row once per iteration).

    Returns a dict with per-wave and per-iteration byte/row counters;
    obs.metrics carries it as the ``hist_traffic`` meta entry and
    bench.py folds it into its JSON line."""
    import math as _math

    if pack_vpb is None:
        # default: the packing factor tpu_bin_pack=auto would pick for
        # this bin width (callers pass the ACTUAL vpb when they know it)
        from .ops.bin_pack import pack_vpb as _pack_vpb
        pack_vpb = _pack_vpb(max_bins)
    bin_bytes = storage_features * _math.ceil(num_data / pack_vpb)
    if waved:
        sizes = _wave_schedule(num_leaves, wave_max, slots,
                               1 if subtract else 2)
        passes = len(sizes)  # root + per-wave boundaries (last skipped)
    else:
        sizes = [1] * (num_leaves - 1)
        passes = num_leaves  # root + one masked full-data build per split
    per_pass = bin_bytes + num_data * (gh_read_bytes + row_leaf_bytes)
    grad_pass_bytes = 0 if fused_grad else num_data * 24
    return {
        "passes": passes,
        "wave_sizes": sizes,
        "rows_scanned_per_iter": passes * num_data,
        "wave_rows_scanned": [num_data] * passes,
        "bytes_per_pass": per_pass,
        "bin_bytes_per_pass": bin_bytes,
        "grad_pass_bytes": grad_pass_bytes,
        "hist_bytes_per_iter": passes * per_pass + grad_pass_bytes,
        "pack_vpb": pack_vpb,
        "gh_read_bytes": gh_read_bytes,
        "subtract": subtract,
        "fused_grad": fused_grad,
    }


def collective_traffic_model(*, num_features: int, max_bins: int,
                             num_leaves: int, wave_max: int, width: int,
                             reduction: str = "psum", dcn: int = 1,
                             slots: int = HIST_SLOTS,
                             subtract: bool = True, waved: bool = True):
    """Static per-iteration COLLECTIVE traffic model of the mesh grower
    — the byte counterpart of ``hist_traffic_model`` for what crosses
    the interconnect rather than HBM. Exact for the compiled program:
    wave schedule, feature padding and payload record sizes are all
    trace-time constants, and the runtime ``collectives`` counters use
    the same per-shard-result byte convention (obs/health.py), so model
    and counters agree by construction.

    reduction="psum": every histogram pass all-reduces the full
    [S, F, B, 3] slab (per-shard result bytes = the full slab).
    reduction="scatter": each pass reduce-scatters the zero-padded
    [S, Fp, B, 3] slab over ``width`` shards (per-shard result = 1/W of
    it) and every split-search batch all_gathers ``width`` SplitInfo
    records per tree position — O(W * sizeof(SplitInfo)), not
    O(F * B). With ``dcn`` > 1 (hierarchical mesh) the owned 1/W slice
    additionally psums over the slow inter-host link: ``dcn_bytes``
    prices that leg separately since DCN bandwidth, not ICI, is the
    multi-host ceiling.

    width: shards on the scatter (last, ICI) mesh axis; dcn: process
    groups on the outer axis (1 = flat single-host mesh)."""
    from .ops.split import split_info_nbytes

    f_pad = -(-num_features // max(width, 1)) * max(width, 1)
    if waved:
        sizes = _wave_schedule(num_leaves, wave_max, slots,
                               1 if subtract else 2)
        # root pass + one boundary per wave (the last is skipped);
        # boundary passes build S (or 2S) slots and search 2S children
        hist_slots = [1] + [(s if subtract else 2 * s)
                            for s in sizes[:-1]]
        search_records = 1 + 2 * sum(sizes[:-1])
    else:
        hist_slots = [1] * num_leaves  # root + smaller child per split
        search_records = 1 + 2 * (num_leaves - 1)
    slab = max_bins * 3 * 4  # one feature row: [B, 3] x 4-byte elems
    if reduction == "psum":
        hist_bytes = sum(hist_slots) * num_features * slab
        split_bytes = 0
        dcn_bytes = 0
    else:
        hist_bytes = sum(hist_slots) * (f_pad // max(width, 1)) * slab
        split_bytes = search_records * width * split_info_nbytes(max_bins)
        dcn_bytes = (hist_bytes if dcn > 1 else 0)
    return {
        "reduction": reduction,
        "width": width,
        "dcn": dcn,
        "padded_features": f_pad,
        "hist_collective_bytes_per_iter": hist_bytes,
        "split_collective_bytes_per_iter": split_bytes,
        "dcn_bytes_per_iter": dcn_bytes,
        "collective_bytes_per_iter": hist_bytes + split_bytes + dcn_bytes,
        "split_records_per_iter": search_records,
        "split_info_nbytes": split_info_nbytes(max_bins),
    }


def _wave_step_stored(carry, step_idx, *, L, meta, hp, unknown,
                      mono_pairwise, partition_fn=None):
    """One stored-candidate split application (no histogram builds) —
    the scan body shared by the resident waved grower and the streamed
    grower's wave-apply program (the streamed twin must run the SAME
    traced ops so models stay bit-identical across the modes).

    ``partition_fn(row_leaf, best, new, feat, thr, dleft, cmask, valid)``
    applies the split to row_leaf immediately (the per-split partition
    path); None leaves row_leaf untouched (batched wave partition, or
    the streamed grower where partition runs per slab).

    Invalid steps use the out-of-bounds id L: every .at[] write to it
    is dropped (jit scatter semantics), so a dummy can never clobber a
    real leaf's slot."""
    row_leaf, leaves, used, n_applied, box_lo, box_hi = carry
    best_leaf = jnp.argmax(leaves.gain).astype(jnp.int32)
    valid = leaves.gain[best_leaf] > 0.0
    new_leaf = jnp.where(valid, n_applied + 1, L).astype(jnp.int32)
    n_applied = n_applied + valid.astype(jnp.int32)
    feat = leaves.feature[best_leaf]
    thr = leaves.threshold[best_leaf]
    dleft = leaves.default_left[best_leaf]
    cmask = leaves.cat_mask[best_leaf]

    if partition_fn is not None:
        row_leaf = partition_fn(row_leaf, best_leaf, new_leaf, feat, thr,
                                dleft, cmask, valid)

    pg, ph, pc = (leaves.sum_grad[best_leaf], leaves.sum_hess[best_leaf],
                  leaves.count[best_leaf])
    lg = leaves.left_sum_grad[best_leaf]
    lh = leaves.left_sum_hess[best_leaf]
    lc = leaves.left_count[best_leaf]
    rg, rh, rc = pg - lg, ph - lh, pc - lc
    parent_out = leaves.output[best_leaf]
    p_minb = leaves.min_bound[best_leaf]
    p_maxb = leaves.max_bound[best_leaf]
    out_l = leaves.left_output[best_leaf]
    out_r = leaves.right_output[best_leaf]
    chosen_gain = leaves.gain[best_leaf]

    if mono_pairwise:
        # bounds may have tightened since this candidate was stored
        out_l = jnp.clip(out_l, p_minb, p_maxb)
        out_r = jnp.clip(out_r, p_minb, p_maxb)
        box_lo, box_hi = split_ops.split_child_boxes(
            box_lo, box_hi, best_leaf, new_leaf, feat, thr,
            meta.is_categorical[feat], valid)
        out_now = leaves.output.at[best_leaf].set(
            jnp.where(valid, out_l, parent_out))
        ni = jnp.minimum(new_leaf, L - 1)
        out_now = out_now.at[new_leaf].set(
            jnp.where(valid, out_r, out_now[ni]))
        leaf_in_use = jnp.arange(L, dtype=jnp.int32) <= n_applied
        minb_all, maxb_all = split_ops.compute_box_bounds(
            box_lo, box_hi, out_now, leaf_in_use, meta.monotone)
        leaves = leaves._replace(
            min_bound=jnp.where(valid, minb_all, leaves.min_bound),
            max_bound=jnp.where(valid, maxb_all, leaves.max_bound))
        l_min, l_max = minb_all[best_leaf], maxb_all[best_leaf]
        r_min, r_max = minb_all[ni], maxb_all[ni]
    else:
        l_min, l_max, r_min, r_max = split_ops.propagate_monotone_bounds(
            out_l, out_r, meta.monotone[feat].astype(jnp.int32),
            meta.is_categorical[feat], p_minb, p_maxb)

    if used is not None:
        child_used = used[best_leaf].at[feat].set(True)
        used = used.at[best_leaf].set(
            jnp.where(valid, child_used, used[best_leaf]))
        used = used.at[new_leaf].set(
            jnp.where(valid, child_used, used[new_leaf]))

    child_depth = leaves.depth[best_leaf] + 1
    # children have no candidates until the wave-boundary build
    leaves = _store_split(leaves, best_leaf, unknown, child_depth,
                          out_l, lg, lh, lc, l_min, l_max, valid)
    leaves = _store_split(leaves, new_leaf, unknown, child_depth,
                          out_r, rg, rh, rc, r_min, r_max, valid)

    left_smaller = lc <= rc
    record = dict(
        split_leaf=jnp.where(valid, best_leaf, -1),
        split_feature=feat,
        split_bin_threshold=thr,
        split_default_left=dleft,
        split_gain=jnp.where(valid, chosen_gain, 0.0),
        split_cat_mask=cmask,
        internal_value=parent_out,
        internal_weight=ph,
        internal_count=pc,
    )
    ys = dict(record=record, valid=valid,
              left_id=best_leaf, right_id=new_leaf,
              small_id=jnp.where(left_smaller, best_leaf, new_leaf),
              left_smaller=left_smaller)
    return (row_leaf, leaves, used, n_applied, box_lo, box_hi), ys


def _unknown_split(max_bins: int) -> SplitInfo:
    """The no-candidate sentinel stored for freshly-created children
    until the wave boundary builds their histograms."""
    return SplitInfo(
        gain=jnp.float32(K_MIN_SCORE), feature=jnp.int32(0),
        threshold=jnp.int32(0), default_left=jnp.bool_(False),
        left_sum_grad=jnp.float32(0), left_sum_hess=jnp.float32(0),
        left_count=jnp.float32(0), right_sum_grad=jnp.float32(0),
        right_sum_hess=jnp.float32(0), right_count=jnp.float32(0),
        left_output=jnp.float32(0), right_output=jnp.float32(0),
        cat_mask=jnp.zeros((max_bins,), jnp.bool_))


def _init_wave_state(root_hist, root_g, root_h, root_c, meta, hp,
                     root_fmask, node_key, *, L, max_bins, num_features,
                     f32, has_categorical, extra_trees, ff_bynode,
                     interaction_groups, split_fn=None):
    """Root leaf state + histogram pool from a built root histogram —
    shared by the resident waved grower and the streamed grower (the
    streamed root histogram arrives accumulated over slabs).

    split_fn: optional find_best_split replacement (signature minus
    has_categorical) — the feature-sharded scatter search
    (parallel/scatter.py). The pool then inherits the (possibly
    feature-padded) built histogram's shape."""
    neg_inf, pos_inf = jnp.float32(-jnp.inf), jnp.float32(jnp.inf)
    root_out = leaf_output(root_g, root_h, hp)
    rb_root, fm_root = _node_randomness(node_key, 0, meta, root_fmask,
                                        extra_trees, ff_bynode)
    if split_fn is None:
        root_split = find_best_split(root_hist, root_g, root_h, root_c,
                                     meta, hp, fm_root, root_out,
                                     neg_inf, pos_inf, jnp.int32(0),
                                     has_categorical, rb_root)
    else:
        root_split = split_fn(root_hist, root_g, root_h, root_c,
                              meta, hp, fm_root, root_out,
                              neg_inf, pos_inf, jnp.int32(0), rb_root)

    zero_l = jnp.zeros((L,), f32)
    leaves = _LeafSplits(
        sum_grad=zero_l, sum_hess=zero_l, count=zero_l,
        depth=jnp.zeros((L,), jnp.int32),
        output=zero_l,
        gain=jnp.full((L,), K_MIN_SCORE, f32),
        feature=jnp.zeros((L,), jnp.int32),
        threshold=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), jnp.bool_),
        left_sum_grad=zero_l, left_sum_hess=zero_l, left_count=zero_l,
        left_output=zero_l, right_output=zero_l,
        cat_mask=jnp.zeros((L, max_bins), jnp.bool_),
        min_bound=jnp.full((L,), -jnp.inf, f32),
        max_bound=jnp.full((L,), jnp.inf, f32),
    )
    leaves = _store_split(leaves, 0, root_split, jnp.int32(1), root_out,
                          root_g, root_h, root_c, neg_inf, pos_inf, True)
    pool = jnp.zeros((L,) + tuple(root_hist.shape), f32)
    pool = pool.at[0].set(root_hist)
    used = (jnp.zeros((L, num_features), bool)
            if interaction_groups is not None else None)
    return leaves, pool, used


def _wave_boundary_core(pool, leaves, used_features, ys, wave_hists,
                        feature_mask, max_depth, node_key, s0, *,
                        subtract_siblings, L, num_features, f32, meta, hp,
                        interaction_groups, has_categorical, extra_trees,
                        ff_bynode, split_fn=None):
    """Wave-boundary histogram bookkeeping + child candidate search,
    given the wave's built histograms (`wave_hists`: the W smaller
    children under subtraction, or both-children [2W] in oracle mode).
    Shared by the resident waved grower (which builds wave_hists with
    one resident multi-leaf pass) and the streamed grower (which
    accumulates them over host-fed slabs).

    split_fn: optional BATCHED find_best_split replacement taking the
    [2W]-leading child histograms/stats (the feature-sharded scatter
    search); None runs the stock replicated vmap."""
    W = ys["valid"].shape[0]
    if subtract_siblings:
        parents = pool[ys["left_id"]]                      # [W, F, B, 3]
        small_h = wave_hists.astype(f32)
        large_h = hist_ops.subtract_histogram(parents, small_h)
        ls = ys["left_smaller"][:, None, None, None]
        left_h = jnp.where(ls, small_h, large_h)
        right_h = jnp.where(ls, large_h, small_h)
    else:
        left_h = wave_hists[:W].astype(f32)
        right_h = wave_hists[W:].astype(f32)
    left_w = jnp.where(ys["valid"], ys["left_id"], L)
    right_w = jnp.where(ys["valid"], ys["right_id"], L)
    pool = pool.at[left_w].set(left_h)
    pool = pool.at[right_w].set(right_h)

    def child_candidates(hist, cid, fmask_c, salt, leaves):
        """find_best_split for one child from its stored stats."""
        rb, fm = _node_randomness(node_key, salt, meta, fmask_c,
                                  extra_trees, ff_bynode)
        return find_best_split(
            hist, leaves.sum_grad[cid], leaves.sum_hess[cid],
            leaves.count[cid], meta, hp, fm, leaves.output[cid],
            leaves.min_bound[cid], leaves.max_bound[cid],
            leaves.depth[cid] - 1, has_categorical, rb)

    # --- candidates for the 2W children, batched
    child_ids = jnp.concatenate([ys["left_id"], ys["right_id"]])
    child_valid = jnp.concatenate([ys["valid"], ys["valid"]])
    hists = pool[child_ids]
    if used_features is not None:
        fmask_c = feature_mask[None, :] & jax.vmap(
            _allowed_features, in_axes=(0, None))(
                used_features[child_ids], interaction_groups)
    else:
        fmask_c = jnp.broadcast_to(feature_mask, (2 * W, num_features))
    salts = 2 * s0 + jnp.arange(2 * W, dtype=jnp.int32)
    if split_fn is None:
        infos = jax.vmap(child_candidates, in_axes=(0, 0, 0, 0, None))(
            hists, child_ids, fmask_c, salts, leaves)
    else:
        # same per-node randomness as the vmapped oracle, then ONE
        # batched feature-sharded search over the 2W children
        if node_key is None:
            rbs, fms = None, fmask_c
        else:
            rbs, fms = jax.vmap(
                lambda s, f: _node_randomness(node_key, s, meta, f,
                                              extra_trees, ff_bynode))(
                salts, fmask_c)
        infos = split_fn(hists, leaves.sum_grad[child_ids],
                         leaves.sum_hess[child_ids],
                         leaves.count[child_ids], meta, hp, fms,
                         leaves.output[child_ids],
                         leaves.min_bound[child_ids],
                         leaves.max_bound[child_ids],
                         leaves.depth[child_ids] - 1, rbs)
    depth_ok = (max_depth <= 0) | (leaves.depth[child_ids] < max_depth)
    gains = jnp.where(child_valid & depth_ok, infos.gain, K_MIN_SCORE)

    def upd(arr, val):
        keep = arr[child_ids]
        return arr.at[child_ids].set(
            jnp.where(child_valid.reshape(
                (-1,) + (1,) * (val.ndim - 1)), val, keep))
    leaves = leaves._replace(
        gain=leaves.gain.at[child_ids].set(
            jnp.where(child_valid, gains, leaves.gain[child_ids])),
        feature=upd(leaves.feature, infos.feature),
        threshold=upd(leaves.threshold, infos.threshold),
        default_left=upd(leaves.default_left, infos.default_left),
        left_sum_grad=upd(leaves.left_sum_grad, infos.left_sum_grad),
        left_sum_hess=upd(leaves.left_sum_hess, infos.left_sum_hess),
        left_count=upd(leaves.left_count, infos.left_count),
        left_output=upd(leaves.left_output, infos.left_output),
        right_output=upd(leaves.right_output, infos.right_output),
        cat_mask=upd(leaves.cat_mask, infos.cat_mask),
    )
    return pool, leaves


def grow_tree_waved(bins_fm: jax.Array,
                    grad: jax.Array,
                    hess: jax.Array,
                    sample_mask: jax.Array,
                    feature_mask: jax.Array,
                    meta: FeatureMeta,
                    hp: SplitHyperParams,
                    max_depth: jax.Array,
                    forced: Optional[tuple] = None,
                    node_key: Optional[jax.Array] = None,
                    *,
                    num_leaves: int,
                    max_bins: int,
                    hist_dtype=jnp.float32,
                    hist_impl: str = "xla",
                    hist_precision: str = "highest",
                    interaction_groups=None,
                    has_categorical: bool = True,
                    wave_max: int = 32,
                    extra_trees: bool = False,
                    ff_bynode: float = 1.0,
                    quant: Optional[tuple] = None,
                    bundle=None,
                    num_bundle_bins: int = 0,
                    mono_pairwise: bool = False,
                    shard_mesh=None,
                    hist_reduce: str = "psum",
                    sparse_shape=None,
                    batched_partition=None,
                    fused_grad=None,
                    subtract_siblings: bool = True,
                    hist_deterministic: bool = False):
    """Leaf-wise growth with waved (batched) histogram construction.

    fused_grad: optional (pointwise_fn, label, weight_or_None, score)
    from the objective (objectives.pointwise_grad_fn): grad/hess are
    then DERIVED inside the grower — bitwise-identical formulas to
    objective.get_gradients — instead of arriving as materialized [N]
    buffers, and on the pallas path the multi-leaf kernel computes them
    IN-KERNEL from (score, label[, weight], mask), so the standalone
    gradient/bagging element-wise pass and the [N, 3] ghT round-trip
    through HBM disappear (~0.5 GB/iter of the cost model). The
    `grad`/`hess` arguments may be None in this mode.

    subtract_siblings: True (default) builds each split's SMALLER child
    and derives the larger by subtraction from the pooled parent
    (ref: serial_tree_learner.cpp:582); the wave schedule packs one
    slot per split. False is the no-subtraction ORACLE: both children
    are built directly (two slots per split, more waves) — retained for
    A/B parity checks and the traffic counters' baseline.

    hist_deterministic: Kahan-compensated fixed-chunk accumulation in
    the XLA histogram paths (`deterministic_hist` knob).

    batched_partition: apply each wave's splits in one gathered pass
    (partition.apply_wave_splits) instead of per-split passes. None =
    auto: on for accelerator backends (the gather is an HBM-bandwidth
    win), off on CPU (the gather loses to sequential masked passes) and
    always off for COO sparse storage.

    Identical split mathematics to `grow_tree`, but histogram builds are
    batched: splits are applied in waves; at each wave boundary ONE
    multi-leaf pass (ops/pallas_histogram.hist_multi) builds the smaller
    children of all the wave's splits simultaneously, and siblings come
    from subtraction. This turns the reference's per-leaf histogram
    kernels (cuda_histogram_constructor.cu:21 — one launch per leaf,
    touching that leaf's rows) into ~log2(num_leaves)+L/slots full-data
    passes — the shape the TPU MXU wants.

    Semantics vs exact leaf-wise: within a wave, freshly-created children
    are not yet split candidates (their histograms arrive at the wave
    boundary). Wave sizes grow geometrically from 1, so the early,
    high-impact splits are chosen exactly as in `grow_tree`.

    Forced splits are not supported (the caller falls back to
    `grow_tree`).

    quant: optional (g_int [N] int-valued f32, h_int [N] int-valued f32,
    g_scale, h_scale) from the gradient discretizer. The histogram
    passes then run the int8 x int8 -> int32 kernel — the MXU pallas
    kernel on device backends (exact integer accumulation at twice the
    bf16 rate, the TPU shape of the reference's quantized histograms,
    gradient_discretizer.hpp:23), its exact-integer XLA twin elsewhere
    — and the int32 results are scaled back to the f32 statistics. The
    `grad`/`hess` arguments must already be the dequantized values
    (g_int * g_scale) so all non-histogram math is unchanged.
    """
    assert forced is None, "waved growth does not support forced splits"
    from .ops.pallas_histogram import (hist_multi, hist_multi_int8,
                                       hist_pallas_multi_fused)

    if sparse_shape is not None:
        assert bundle is None and quant is None, \
            "sparse COO storage composes with neither EFB nor int8 hist"
        num_features, num_data = sparse_shape
    else:
        num_data = bins_fm.shape[1]
        num_features = (bins_fm.shape[0] if bundle is None
                        else bundle[0].shape[0])
    L = num_leaves
    f32 = hist_dtype
    SLOTS = HIST_SLOTS  # 128 MXU columns // 3 channels
    build_bins = max_bins if bundle is None else num_bundle_bins

    use_mesh = shard_mesh is not None and shard_mesh.size > 1
    if (not use_mesh or bundle is not None or sparse_shape is not None):
        # scatter needs shard_map histogram builds over the raw bins;
        # EFB/COO storage builds don't run there — psum oracle instead
        hist_reduce = "psum"
    use_shard_hist = use_mesh and (hist_impl == "pallas"
                                   or hist_reduce == "scatter")
    use_kernel_fused = False
    if fused_grad is not None:
        assert quant is None and sparse_shape is None, \
            "fused gradients compose with neither int8 hist nor COO"
        fg_fn, fg_label, fg_weight, fg_score = fused_grad
        # derive grad/hess from the pointwise objective — bitwise the
        # same values get_gradients would have produced, but XLA can now
        # fuse the element-wise math straight into its consumers instead
        # of round-tripping materialized [N] buffers through HBM
        grad, hess = fg_fn(fg_score, fg_label, fg_weight)
        # build_bins <= 256 keeps bin ids byte-representable — the fused
        # kernel reads bins through the byte-sectioned layout, so uint16
        # storage (max_bin > 256) must stay on the materialized-ghT path
        use_kernel_fused = (hist_impl == "pallas" and bundle is None
                            and shard_mesh is None and build_bins <= 256)
    if sparse_shape is not None:
        def multi_raw(bins, ghT_, row_leaf, ids):
            # O(nnz) segment-sum wave pass (the sparse row-wise
            # MultiValBin analog, multi_val_sparse_bin.hpp:70)
            return hist_ops.hist_multi_sparse(
                bins, ghT_, row_leaf, ids, num_features=num_features,
                max_bins=max_bins, num_slots=ids.shape[0])
    elif quant is not None:
        g_int, h_int, g_scale, h_scale = quant
        m8 = sample_mask.astype(jnp.int8)
        ghT_i8 = jnp.stack([g_int.astype(jnp.int8) * m8,
                            h_int.astype(jnp.int8) * m8, m8], axis=1)
        hscale_vec = jnp.stack([g_scale, h_scale,
                                jnp.float32(1.0)]).astype(f32)
        if use_shard_hist:
            # per-shard int8 kernel + INT32 psum: the cross-mesh reduce
            # moves exact integer histograms and dequantizes after —
            # the collective analog of the reference's quantized
            # histogram reduction (data_parallel_tree_learner.cpp:290)
            _multi_i32 = _sharded_pallas_multi(
                shard_mesh, max_bins=build_bins,
                precision=hist_precision, int8=True, impl=hist_impl,
                hist_reduce=hist_reduce,
                deterministic=hist_deterministic)

            def multi_raw(bins, ghT_unused, row_leaf, ids):
                return _multi_i32(bins, ghT_i8, row_leaf,
                                  ids).astype(f32) * hscale_vec
        else:
            # default-capable on every backend: the pallas MXU kernel
            # where Mosaic runs, the exact-integer XLA contraction
            # elsewhere — identical int32 histograms either way
            def multi_raw(bins, ghT_unused, row_leaf, ids):
                hist_i = hist_multi_int8(bins, ghT_i8, row_leaf, ids,
                                         max_bins=build_bins,
                                         num_slots=ids.shape[0],
                                         impl=hist_impl)
                return hist_i.astype(f32) * hscale_vec
    elif use_kernel_fused:
        def multi_raw(bins, ghT_unused, row_leaf, ids):
            # gradient pass fused INTO the histogram kernel: reads
            # (score, label[, weight], mask) and computes gh in VMEM —
            # ghT never exists in HBM (see hist_pallas_multi_fused)
            return hist_pallas_multi_fused(
                bins, fg_score, fg_label, fg_weight, sample_mask,
                row_leaf, ids, grad_fn=fg_fn, max_bins=build_bins,
                num_slots=ids.shape[0], precise=hist_precision)
    elif use_shard_hist:
        multi_raw = _sharded_pallas_multi(
            shard_mesh, max_bins=build_bins, precision=hist_precision,
            int8=False, impl=hist_impl, hist_reduce=hist_reduce,
            deterministic=hist_deterministic)
    else:
        def multi_raw(bins, ghT_, row_leaf, ids):
            # num_slots = the wave's LIVE count: the pallas kernel's cost
            # is fixed (128 lanes) either way, but the XLA fallback loops
            # one build per slot, so early 1-8 split waves must not pay
            # for 42
            return hist_multi(bins, ghT_, row_leaf, ids,
                              max_bins=build_bins, num_slots=ids.shape[0],
                              impl=hist_impl, precision=hist_precision,
                              deterministic=hist_deterministic)
    if bundle is None:
        multi = multi_raw
    else:
        from .bundling import expand_bundle_hist
        group_of, offset_of, nb_arr = bundle

        def multi(bins, ghT_, row_leaf, ids):
            hg = multi_raw(bins, ghT_, row_leaf, ids)  # [S, G, B_tot, 3]
            totals = jnp.sum(hg[:, 0], axis=1)  # [S, 3]
            return expand_bundle_hist(hg, group_of, offset_of, nb_arr,
                                      max_bins, totals)
    # the gradient/bagging element-wise product: skipped entirely when
    # the kernel computes gh in-place (fused_grad on the pallas path)
    ghT = None if use_kernel_fused else jnp.stack(
        [grad * sample_mask, hess * sample_mask, sample_mask],
        axis=1).astype(jnp.float32)

    if interaction_groups is not None:
        interaction_groups = jnp.asarray(interaction_groups, bool)
        root_allowed = jnp.any(interaction_groups, axis=0)
    else:
        root_allowed = None

    # --- root: one slot of the multi-leaf kernel (every row is in leaf 0).
    # The single-leaf kernel's [3, C] x [C, B] dots leave the MXU 97% idle
    # (M=3 rows); the multi kernel's [f_blk*B, C] x [C, 128] shape is the
    # efficient one, so the root rides it too.
    root_ids = jnp.zeros((1,), jnp.int32)
    root_hist = multi(bins_fm, ghT, jnp.zeros((num_data,), jnp.int32),
                      root_ids)[0].astype(f32)
    root_g = jnp.sum(grad * sample_mask, dtype=f32)
    root_h = jnp.sum(hess * sample_mask, dtype=f32)
    root_c = jnp.sum(sample_mask, dtype=f32)
    root_fmask = feature_mask if root_allowed is None else \
        feature_mask & root_allowed
    if hist_reduce == "scatter":
        from .parallel.scatter import make_scatter_split
        _scat_kw = dict(num_features=num_features,
                        hist_features=root_hist.shape[0],
                        has_categorical=has_categorical)
        split_root_fn = make_scatter_split(shard_mesh, batched=False,
                                           **_scat_kw)
        # one batched search per wave boundary: [2W] children gather as
        # ONE all_gather of 2W SplitInfo records per shard
        split_wave_fn = make_scatter_split(shard_mesh, batched=True,
                                           **_scat_kw)
    else:
        split_root_fn = split_wave_fn = None
    leaves, pool, used_features = _init_wave_state(
        root_hist, root_g, root_h, root_c, meta, hp, root_fmask, node_key,
        L=L, max_bins=max_bins, num_features=num_features, f32=f32,
        has_categorical=has_categorical, extra_trees=extra_trees,
        ff_bynode=ff_bynode, interaction_groups=interaction_groups,
        split_fn=split_root_fn)
    row_leaf = jnp.zeros((num_data,), jnp.int32)

    unknown = _unknown_split(max_bins)

    def wave_step(carry, step_idx):
        """Apply one split using STORED candidates only (no histograms).

        New-leaf ids come from the APPLIED-split counter, not the scan
        step: a step can be invalid (stale candidates all <= 0) while a
        later wave revives growth with fresh candidates, and gap-free
        ids are what Tree.from_arrays and the score updater index by.
        """
        if use_batched_partition:
            partition_fn = None
        else:
            # per-split partition: COO storage can't serve the batched
            # pass's per-row feature gathers, and on CPU the gather is
            # slower than W sequential masked passes (measured: bench
            # fallback 3.6 -> 2.8 s/iter) — the batched pass is an HBM
            # bandwidth optimization for accelerator backends
            def partition_fn(row_leaf, best_leaf, new_leaf, feat, thr,
                             dleft, cmask, valid):
                return part_ops.apply_split(
                    row_leaf, bins_fm, best_leaf, new_leaf, feat, thr,
                    dleft, cmask, meta.num_bins, meta.missing_type,
                    meta.is_categorical, valid, bundle)
        return _wave_step_stored(carry, step_idx, L=L, meta=meta, hp=hp,
                                 unknown=unknown,
                                 mono_pairwise=mono_pairwise,
                                 partition_fn=partition_fn)

    if batched_partition is None:
        batched_partition = not hist_ops.cpu_backend()
    use_batched_partition = sparse_shape is None and batched_partition

    all_records = []
    all_valid = []
    s0 = 0
    n_applied = jnp.int32(0)
    wbox_lo = (jnp.zeros((L, num_features), jnp.int32)
               if mono_pairwise else None)
    wbox_hi = (jnp.full((L, num_features), max_bins - 1, jnp.int32)
               if mono_pairwise else None)
    schedule = _wave_schedule(L, wave_max, SLOTS,
                              1 if subtract_siblings else 2)
    for wi, W in enumerate(schedule):
        (row_leaf, leaves, used_features, n_applied, wbox_lo, wbox_hi), \
            ys = lax.scan(
                wave_step,
                (row_leaf, leaves, used_features, n_applied,
                 wbox_lo, wbox_hi),
                jnp.arange(s0, s0 + W, dtype=jnp.int32))
        all_records.append(ys["record"])
        all_valid.append(ys["valid"])
        s0 += W

        if use_batched_partition:
            # ONE batched partition pass for the whole wave (dense/EFB
            # layouts on accelerator backends; each row moves at most
            # once per wave — see partition.apply_wave_splits). The COO
            # and CPU paths partitioned inside wave_step instead.
            row_leaf = part_ops.apply_wave_splits(
                row_leaf, bins_fm, ys["left_id"], ys["right_id"],
                ys["record"]["split_feature"],
                ys["record"]["split_bin_threshold"],
                ys["record"]["split_default_left"],
                ys["record"]["split_cat_mask"], ys["valid"],
                meta.num_bins, meta.missing_type, meta.is_categorical,
                L, bundle)

        if wi == len(schedule) - 1:
            # the tree is full: the children of the final wave can never
            # be split, so their histograms/candidates are dead weight —
            # skip the boundary pass entirely (saves 1 of ~13 full-data
            # passes at 255 leaves)
            break

        # --- wave boundary: ONE multi-leaf pass builds all the wave's
        # smaller children; siblings come from subtraction
        # (ref: serial_tree_learner.cpp:582 histogram subtraction).
        # One batched gather + two batched scatters instead of a W-long
        # unrolled chain: a wave's split leaves are pairwise distinct
        # (a split leaf's candidate becomes `unknown` within the wave),
        # and invalid steps write to the out-of-bounds row L, which jit
        # scatters drop — so the batch has no index collisions.
        if subtract_siblings:
            small_ids = jnp.where(ys["valid"], ys["small_id"], -2)
            wave_hists = multi(bins_fm, ghT, row_leaf,
                               small_ids)              # [W, F, B, 3]
        else:
            # no-subtraction ORACLE (tpu_wave_subtract=False): build BOTH
            # children directly. Two slots per split — the schedule above
            # already halved the wave width — and the pass accumulates
            # the rows of the full frontier instead of only the smaller
            # siblings. Kept as the parity/traffic baseline.
            lids = jnp.where(ys["valid"], ys["left_id"], -2)
            rids = jnp.where(ys["valid"], ys["right_id"], -2)
            wave_hists = multi(bins_fm, ghT, row_leaf,
                               jnp.concatenate([lids, rids]))
        pool, leaves = _wave_boundary_core(
            pool, leaves, used_features, ys, wave_hists,
            feature_mask, max_depth, node_key, s0,
            subtract_siblings=subtract_siblings, L=L,
            num_features=num_features, f32=f32, meta=meta, hp=hp,
            interaction_groups=interaction_groups,
            has_categorical=has_categorical, extra_trees=extra_trees,
            ff_bynode=ff_bynode, split_fn=split_wave_fn)

    records = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *all_records)
    # compact: valid splits first, in application order. A stale-candidate
    # step can be invalid while later waves keep splitting, so raw scan
    # order may interleave -1 records among real ones; Tree.from_arrays
    # and replay_tree index split s -> new leaf s+1, which requires the
    # gap-free prefix this permutation restores.
    valid_all = jnp.concatenate(all_valid)
    steps = jnp.arange(L - 1, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(valid_all, steps, steps + L))
    records = jax.tree_util.tree_map(lambda a: a[order], records)
    num_leaves_out = 1 + n_applied

    tree_arrays = TreeArrays(
        split_leaf=records["split_leaf"],
        split_feature=records["split_feature"],
        split_bin_threshold=records["split_bin_threshold"],
        split_default_left=records["split_default_left"],
        split_gain=records["split_gain"],
        split_cat_mask=records["split_cat_mask"],
        internal_value=records["internal_value"],
        internal_weight=records["internal_weight"],
        internal_count=records["internal_count"],
        leaf_value=leaves.output,
        leaf_weight=leaves.sum_hess,
        leaf_count=leaves.count,
        num_leaves=num_leaves_out,
    )
    return tree_arrays, row_leaf


class StreamTreeGrower:
    """Host-orchestrated ``grow_tree_waved`` twin for host-resident bins
    (out-of-core streaming training, ``tpu_stream``).

    Same split mathematics, wave schedule and traced step/boundary ops
    as the resident waved grower (the scan body and boundary math are
    literally shared: ``_wave_step_stored`` / ``_wave_boundary_core`` /
    ``_init_wave_state``); the difference is WHERE the dominant ``[F,
    N]`` bin operand lives. Every full-data pass — the root build and
    each wave's batched partition + boundary histogram build — becomes
    a loop over ``io.streaming.HostSlabBins`` slabs, with slab k+1's
    host->device upload double-buffered behind the program consuming
    slab k (the predict engine's pipeline, factored into
    ``io/streaming.py``).

    Numerics contract: per-slab partial histograms accumulate in slab
    order (slab 0 assigns, later slabs add). With a single slab the
    program consumes the same arrays through the same ops as the
    resident grower => bit-identical models (asserted in
    tests/test_stream.py across the sampling matrix). With int32
    (quantized) histograms the slab partials are exact integer sums
    that are scaled AFTER accumulation, so ANY slab count is
    bit-identical to resident. f32 multi-slab accumulation differs
    from the resident single contraction only by float-add
    associativity (~1 ulp per boundary add).

    Unsupported (callers gate to the resident grower): EFB bundles,
    COO sparse storage, forced splits, interaction constraints,
    pairwise monotone modes, exact (non-waved) growth.
    """

    def __init__(self, plan, *, num_leaves: int, max_bins: int,
                 num_features: int, hist_impl: str, hist_precision: str,
                 has_categorical: bool, extra_trees: bool,
                 ff_bynode: float, wave_max: int, subtract_siblings: bool,
                 hist_deterministic: bool):
        self.plan = plan
        self.L = int(num_leaves)
        self.max_bins = int(max_bins)
        self.num_features = int(num_features)
        self._impl = hist_impl
        self._precision = hist_precision
        self._has_cat = bool(has_categorical)
        self._extra_trees = bool(extra_trees)
        self._ff_bynode = float(ff_bynode)
        self._wave_max = int(wave_max)
        self._subtract = bool(subtract_siblings)
        self._deterministic = bool(hist_deterministic)
        self._progs = {}

    # -- jitted program builders (one callable per kind; jax's jit
    # caches per input shape, so full slabs and the tail slab simply
    # specialize the same callable) ------------------------------------
    def _prog(self, kind: str, builder):
        prog = self._progs.get(kind)
        if prog is None:
            from .obs import xla as obs_xla
            prog = self._progs[kind] = obs_xla.instrumented_jit(
                f"stream/{kind}", builder, phase="train")
        return prog

    def _slab_rows(self, slab) -> int:
        from .ops.bin_pack import PackedBins
        return slab.num_data if isinstance(slab, PackedBins) \
            else int(slab.shape[1])

    def _multi(self, slab, gh_slab, rl_slab, ids):
        from .ops.pallas_histogram import hist_multi, hist_multi_int8
        if gh_slab.dtype == jnp.int8:
            return hist_multi_int8(slab, gh_slab, rl_slab, ids,
                                   max_bins=self.max_bins,
                                   num_slots=ids.shape[0],
                                   impl=self._impl)
        return hist_multi(slab, gh_slab, rl_slab, ids,
                          max_bins=self.max_bins,
                          num_slots=ids.shape[0], impl=self._impl,
                          precision=self._precision,
                          deterministic=self._deterministic)

    @staticmethod
    def _scaled(acc, hscale):
        """int32 (quantized) accumulators dequantize AFTER the cross-
        slab sum — exact integer totals, the property that makes the
        quantized streamed path bit-identical at any slab count."""
        if acc.dtype == jnp.int32:
            return acc.astype(jnp.float32) * hscale
        return acc

    def _gh_slice(self, ghT, lo, n):
        return lax.dynamic_slice_in_dim(ghT, lo, n, axis=0)

    def _run_hist(self, slab, ghT, rl_slab, lo, ids, acc):
        """One slab's histogram contribution (root or wave boundary)."""
        def first(slab_, ghT_, lo_, ids_, rl_):
            gh = self._gh_slice(ghT_, lo_, self._slab_rows(slab_))
            return self._multi(slab_, gh, rl_, ids_)

        def nxt(slab_, ghT_, lo_, ids_, rl_, acc_):
            gh = self._gh_slice(ghT_, lo_, self._slab_rows(slab_))
            return acc_ + self._multi(slab_, gh, rl_, ids_)

        if acc is None:
            return self._prog("hist_first", first)(slab, ghT, lo, ids,
                                                   rl_slab)
        return self._prog("hist_next", nxt)(slab, ghT, lo, ids, rl_slab,
                                            acc)

    def _run_wave_slab(self, slab, ghT, rl_slab, lo, wave, ids, acc,
                       meta, with_hist: bool):
        """One slab's wave work: batched partition, then (except for
        the final wave, whose children can never split) the boundary
        histogram contribution — one upload serves both."""
        def part(slab_, rl_, wave_, meta_):
            return part_ops.apply_wave_splits(
                rl_, slab_, wave_["left_id"], wave_["right_id"],
                wave_["feat"], wave_["thr"], wave_["dleft"],
                wave_["cmask"], wave_["valid"], meta_.num_bins,
                meta_.missing_type, meta_.is_categorical, self.L, None)

        if not with_hist:
            return self._prog("wave_last", part)(slab, rl_slab, wave,
                                                 meta), None

        def part_hist_first(slab_, ghT_, rl_, lo_, wave_, ids_, meta_):
            new_rl = part(slab_, rl_, wave_, meta_)
            gh = self._gh_slice(ghT_, lo_, self._slab_rows(slab_))
            return new_rl, self._multi(slab_, gh, new_rl, ids_)

        def part_hist_next(slab_, ghT_, rl_, lo_, wave_, ids_, meta_,
                           acc_):
            new_rl = part(slab_, rl_, wave_, meta_)
            gh = self._gh_slice(ghT_, lo_, self._slab_rows(slab_))
            return new_rl, acc_ + self._multi(slab_, gh, new_rl, ids_)

        if acc is None:
            return self._prog("wave_first", part_hist_first)(
                slab, ghT, rl_slab, lo, wave, ids, meta)
        return self._prog("wave_next", part_hist_next)(
            slab, ghT, rl_slab, lo, wave, ids, meta, acc)

    def _run_wave_apply(self, leaves, n_applied, steps, meta, hp):
        unknown = _unknown_split(self.max_bins)

        def wave_apply(leaves_, n_applied_, steps_, meta_, hp_):
            def step(carry, s):
                return _wave_step_stored(carry, s, L=self.L, meta=meta_,
                                         hp=hp_, unknown=unknown,
                                         mono_pairwise=False,
                                         partition_fn=None)
            carry, ys = lax.scan(
                step, (jnp.int32(0), leaves_, None, n_applied_, None,
                       None), steps_)
            return carry[1], carry[3], ys

        return self._prog("wave_apply", wave_apply)(leaves, n_applied,
                                                    steps, meta, hp)

    def _run_root_finish(self, acc, hscale, root_g, root_h, root_c,
                         fmask, node_key, meta, hp):
        def root_finish(acc_, hscale_, rg, rh, rc, fmask_, node_key_,
                        meta_, hp_):
            root_hist = self._scaled(acc_, hscale_)[0].astype(jnp.float32)
            leaves, pool, _ = _init_wave_state(
                root_hist, rg, rh, rc, meta_, hp_, fmask_, node_key_,
                L=self.L, max_bins=self.max_bins,
                num_features=self.num_features, f32=jnp.float32,
                has_categorical=self._has_cat,
                extra_trees=self._extra_trees, ff_bynode=self._ff_bynode,
                interaction_groups=None)
            return leaves, pool

        return self._prog("root_finish", root_finish)(
            acc, hscale, root_g, root_h, root_c, fmask, node_key, meta,
            hp)

    def _run_boundary(self, acc, hscale, pool, leaves, ys, fmask,
                      max_depth, node_key, s0, meta, hp):
        def boundary(acc_, hscale_, pool_, leaves_, ys_, fmask_,
                     max_depth_, node_key_, s0_, meta_, hp_):
            wave_hists = self._scaled(acc_, hscale_)
            return _wave_boundary_core(
                pool_, leaves_, None, ys_, wave_hists, fmask_,
                max_depth_, node_key_, s0_,
                subtract_siblings=self._subtract,
                L=self.L, num_features=self.num_features,
                f32=jnp.float32, meta=meta_, hp=hp_,
                interaction_groups=None, has_categorical=self._has_cat,
                extra_trees=self._extra_trees, ff_bynode=self._ff_bynode)

        return self._prog("boundary", boundary)(
            acc, hscale, pool, leaves, ys, fmask, max_depth, node_key,
            s0, meta, hp)

    # -- the grower -----------------------------------------------------
    def grow(self, ghT, hscale, root_sums, feature_mask, meta, hp,
             max_depth, node_key=None):
        """Grow one tree over the host-resident slab plan.

        ghT: device ``[N, 3]`` pre-masked (g, h, m) operand — f32, or
        int8 with ``hscale`` the [3] dequantization vector (f32 passes
        ``hscale=ones``, applied only on int32 accumulators).
        root_sums: (root_g, root_h, root_c) scalars, computed by the
        caller's prep program from the SAME masked gradients.
        Returns (TreeArrays, row_leaf [N]) like the resident growers.
        """
        plan = self.plan
        stats = plan.stats
        root_g, root_h, root_c = root_sums
        root_ids = jnp.zeros((1,), jnp.int32)

        # --- root histogram: one pass over the slabs
        acc = None
        for i, slab in plan.feed():
            lo = jnp.int32(plan.bounds[i][0])
            rl0 = jnp.zeros((self._slab_rows(slab),), jnp.int32)
            acc = self._run_hist(slab, ghT, rl0, lo, root_ids, acc)
            stats.note_dispatch()
        leaves, pool = self._run_root_finish(
            acc, hscale, root_g, root_h, root_c, feature_mask, node_key,
            meta, hp)

        rl_slabs = None  # per-slab row->leaf pieces (lazily zeros)
        n_applied = jnp.int32(0)
        all_records, all_valid = [], []
        s0 = 0
        schedule = _wave_schedule(self.L, self._wave_max, HIST_SLOTS,
                                  1 if self._subtract else 2)
        for wi, W in enumerate(schedule):
            steps = jnp.arange(s0, s0 + W, dtype=jnp.int32)
            leaves, n_applied, ys = self._run_wave_apply(
                leaves, n_applied, steps, meta, hp)
            all_records.append(ys["record"])
            all_valid.append(ys["valid"])
            s0 += W
            last = wi == len(schedule) - 1
            if self._subtract:
                ids = jnp.where(ys["valid"], ys["small_id"], -2)
            else:
                ids = jnp.concatenate(
                    [jnp.where(ys["valid"], ys["left_id"], -2),
                     jnp.where(ys["valid"], ys["right_id"], -2)])
            wave = {"left_id": ys["left_id"], "right_id": ys["right_id"],
                    "feat": ys["record"]["split_feature"],
                    "thr": ys["record"]["split_bin_threshold"],
                    "dleft": ys["record"]["split_default_left"],
                    "cmask": ys["record"]["split_cat_mask"],
                    "valid": ys["valid"]}
            acc = None
            new_rls = []
            for i, slab in plan.feed():
                lo_i, hi_i = plan.bounds[i]
                rl = (rl_slabs[i] if rl_slabs is not None else
                      jnp.zeros((hi_i - lo_i,), jnp.int32))
                rl2, acc = self._run_wave_slab(
                    slab, ghT, rl, jnp.int32(lo_i), wave, ids, acc,
                    meta, with_hist=not last)
                new_rls.append(rl2)
                stats.note_dispatch()
            rl_slabs = new_rls
            stats.waves_total += 1
            if last:
                # the tree is full: the final wave's children can never
                # split, so the boundary pass is skipped — same as the
                # resident grower
                break
            pool, leaves = self._run_boundary(
                acc, hscale, pool, leaves, ys, feature_mask, max_depth,
                node_key, jnp.int32(s0), meta, hp)

        # --- assemble (same compaction as the resident grower)
        records = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *all_records)
        valid_all = jnp.concatenate(all_valid)
        steps_all = jnp.arange(self.L - 1, dtype=jnp.int32)
        order = jnp.argsort(jnp.where(valid_all, steps_all,
                                      steps_all + self.L))
        records = jax.tree_util.tree_map(lambda a: a[order], records)
        row_leaf = (rl_slabs[0] if len(rl_slabs) == 1
                    else jnp.concatenate(rl_slabs))
        tree_arrays = TreeArrays(
            split_leaf=records["split_leaf"],
            split_feature=records["split_feature"],
            split_bin_threshold=records["split_bin_threshold"],
            split_default_left=records["split_default_left"],
            split_gain=records["split_gain"],
            split_cat_mask=records["split_cat_mask"],
            internal_value=records["internal_value"],
            internal_weight=records["internal_weight"],
            internal_count=records["internal_count"],
            leaf_value=leaves.output,
            leaf_weight=leaves.sum_hess,
            leaf_count=leaves.count,
            num_leaves=1 + n_applied,
        )
        return tree_arrays, row_leaf


def replay_tree(tree: TreeArrays, bins_fm, meta: FeatureMeta, bundle=None,
                num_data: Optional[int] = None) -> jax.Array:
    """Re-derive the row -> leaf map of a grown tree on another binned
    dataset (device). Replays the recorded splits in creation order — the
    device analog of updating a validation ScoreUpdater
    (ref: score_updater.hpp:22, gbdt.cpp UpdateScore valid path).
    num_data is required when bins_fm is a SparseBins COO pytree."""
    if num_data is None:
        num_data = bins_fm.shape[1]
    num_splits = tree.split_leaf.shape[0]

    def step(row_leaf, inputs):
        step_idx, leaf, feat, thr, dleft, cmask = inputs
        row_leaf = part_ops.apply_split(
            row_leaf, bins_fm, leaf, step_idx + 1, feat, thr, dleft, cmask,
            meta.num_bins, meta.missing_type, meta.is_categorical, leaf >= 0,
            bundle)
        return row_leaf, None

    row_leaf, _ = lax.scan(
        step, jnp.zeros(num_data, jnp.int32),
        (jnp.arange(num_splits, dtype=jnp.int32), tree.split_leaf,
         tree.split_feature, tree.split_bin_threshold,
         tree.split_default_left, tree.split_cat_mask),
        unroll=2 if num_splits > 1 else 1)
    return row_leaf
