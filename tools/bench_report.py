#!/usr/bin/env python
"""Aggregate the recorded bench trajectory into a trend report.

The driver leaves one ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json`` per
round in the repo root — either a bare bench contract record or the
driver's ``{"n", "cmd", "rc", "tail"}`` wrapper whose ``tail`` embeds
the JSON line bench.py printed (the same shapes
tools/check_perf_gate.py parses). This tool rolls the whole history
into one table, per (metric, platform):

- one row per round: value, vs_baseline, and the observability
  extras a round carried (hist-traffic reduction, compile seconds,
  device-time coverage from the obs/profile roofline record);
- the best recorded value is the floor; any later same-platform round
  more than ``bench.max_value_drop`` (tools/perf_floor.json) below it
  is flagged ``REGRESSION`` — the same band perf-gate check 3 enforces,
  but over the WHOLE trajectory so a slow bleed across rounds is
  visible even when each step stays inside the gate;
- ``--json`` emits the machine-readable document instead of markdown;
  ``--out PATH`` writes to a file instead of stdout.

Exit 0 always: this is a report, not a gate (check_perf_gate.py is
the gate). Usage: python tools/bench_report.py [--json] [--out PATH]
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _platform_of(unit: str) -> str:
    m = re.search(r"platform=(\w+)", unit or "")
    return m.group(1) if m else "tpu"


def _fish_record(blob: Any) -> Optional[Dict[str, Any]]:
    """The bench contract record out of either file shape."""
    if isinstance(blob, dict) and isinstance(blob.get("metric"), str):
        return blob
    if not isinstance(blob, dict):
        return None
    parsed = blob.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("metric"), str):
        return parsed
    for line in reversed(str(blob.get("tail", "")).splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec.get("metric"), str):
                return rec
    return None


def collect(repo: str = REPO) -> List[Tuple[str, Dict[str, Any]]]:
    """[(filename, record)] for every round that left a contract
    record, oldest first (BENCH_* then MULTICHIP_*, each sorted)."""
    out = []
    for pattern in ("BENCH_*.json", "MULTICHIP_*.json"):
        for path in sorted(glob.glob(os.path.join(repo, pattern))):
            try:
                with open(path) as fh:
                    rec = _fish_record(json.load(fh))
            except (OSError, ValueError):
                continue
            if rec is not None:
                out.append((os.path.basename(path), rec))
    return out


def build_report(records: List[Tuple[str, Dict[str, Any]]],
                 max_drop: float) -> Dict[str, Any]:
    """Group per (metric, platform), compute the floor, flag rounds
    below floor x (1 - max_drop)."""
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for fname, rec in records:
        key = (str(rec.get("metric")), _platform_of(rec.get("unit", "")))
        roofline = rec.get("roofline") or {}
        row = {
            "file": fname,
            "value": float(rec.get("value", 0.0) or 0.0),
            "vs_baseline": rec.get("vs_baseline"),
            "hist_bytes_reduction": rec.get("hist_bytes_reduction"),
            "compile_s_total": rec.get("compile_s_total"),
            "profile_coverage": roofline.get("coverage"),
        }
        groups.setdefault(key, []).append(row)
    report: Dict[str, Any] = {"max_value_drop": max_drop, "groups": [],
                              "regressions": []}
    for (metric, platform), rows in sorted(groups.items()):
        best = max(r["value"] for r in rows)
        floor = best * (1.0 - max_drop)
        for r in rows:
            r["regression"] = bool(best > 0 and r["value"] < floor)
            if r["regression"]:
                report["regressions"].append(
                    f"{r['file']}: {metric}[{platform}] value "
                    f"{r['value']:.4f} is >{max_drop:.0%} below the "
                    f"recorded best {best:.4f}")
        report["groups"].append({
            "metric": metric, "platform": platform, "best": best,
            "latest": rows[-1]["value"], "rows": rows})
    return report


def _fmt(value: Any, spec: str = "{:.4f}") -> str:
    return "-" if value is None else spec.format(value)


def render_markdown(report: Dict[str, Any]) -> str:
    lines = ["# Bench trajectory", ""]
    for group in report["groups"]:
        lines.append(f"## {group['metric']} — {group['platform']} "
                     f"(best {group['best']:.4f}, latest "
                     f"{group['latest']:.4f})")
        lines.append("")
        lines.append("| round | value | vs_baseline | hist reduction | "
                     "compile s | profile coverage | flag |")
        lines.append("|---|---|---|---|---|---|---|")
        for r in group["rows"]:
            lines.append(
                f"| {r['file']} | {r['value']:.4f} | "
                f"{_fmt(r['vs_baseline'])} | "
                f"{_fmt(r['hist_bytes_reduction'], '{:.2f}x')} | "
                f"{_fmt(r['compile_s_total'], '{:.2f}')} | "
                f"{_fmt(r['profile_coverage'], '{:.1%}')} | "
                f"{'REGRESSION' if r['regression'] else ''} |")
        lines.append("")
    if report["regressions"]:
        lines.append(f"**{len(report['regressions'])} flagged "
                     "round(s):**")
        lines.extend(f"- {msg}" for msg in report["regressions"])
    elif report["groups"]:
        lines.append("No rounds below the floor band.")
    else:
        lines.append("No bench records found.")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    out_path = None
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    try:
        with open(os.path.join(REPO, "tools", "perf_floor.json")) as fh:
            max_drop = float(json.load(fh)["bench"]["max_value_drop"])
    except (OSError, ValueError, KeyError):
        max_drop = 0.10
    report = build_report(collect(), max_drop)
    text = (json.dumps(report, indent=2) + "\n" if as_json
            else render_markdown(report))
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(text)
        print(f"# wrote {out_path} ({len(report['groups'])} group(s), "
              f"{len(report['regressions'])} flagged)")
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
