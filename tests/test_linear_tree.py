"""Linear trees (ref: linear_tree_learner.cpp, config.h linear_tree,
linear_lambda; model text keys is_linear/leaf_const/leaf_coeff)."""

import numpy as np

from conftest import make_regression

import lightgbm_tpu as lgb
from lightgbm_tpu import Booster, Dataset


def test_linear_tree_beats_piecewise_constant_on_linear_data(rng):
    # a piecewise-linear target: constant trees need many leaves, linear
    # leaves should fit it nearly exactly
    n = 2000
    X = rng.uniform(-2, 2, (n, 3))
    y = np.where(X[:, 0] > 0, 3.0 * X[:, 1] + 1.0, -2.0 * X[:, 1])
    common = {"objective": "regression", "verbosity": -1, "num_leaves": 4,
              "min_data_in_leaf": 20}
    b_const = lgb.train(common, Dataset(X, label=y), num_boost_round=10)
    b_lin = lgb.train({**common, "linear_tree": True},
                      Dataset(X, label=y), num_boost_round=10)
    mse_const = ((y - b_const.predict(X)) ** 2).mean()
    mse_lin = ((y - b_lin.predict(X)) ** 2).mean()
    assert mse_lin < mse_const * 0.5, (mse_const, mse_lin)


def test_linear_tree_save_load_roundtrip(tmp_path, rng):
    X = rng.uniform(-2, 2, (800, 4))
    y = 2.0 * X[:, 0] + X[:, 1] * X[:, 2]
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "linear_tree": True, "num_leaves": 8},
                    Dataset(X, label=y), num_boost_round=5)
    path = tmp_path / "linear_model.txt"
    bst.save_model(path)
    text = path.read_text()
    assert "is_linear=1" in text
    assert "leaf_coeff=" in text
    loaded = Booster(model_file=str(path))
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_linear_tree_nan_falls_back_to_leaf_value(rng):
    X = rng.uniform(-2, 2, (800, 3))
    y = 3.0 * X[:, 1] + X[:, 0]
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "linear_tree": True, "num_leaves": 6},
                    Dataset(X, label=y), num_boost_round=3)
    Xq = X[:10].copy()
    Xq[:, 1] = np.nan
    preds = bst.predict(Xq)
    assert np.all(np.isfinite(preds))


def test_linear_lambda_regularizes(rng):
    X = rng.uniform(-1, 1, (400, 2))
    y = 5.0 * X[:, 0] + 0.1 * rng.randn(400)
    b_small = lgb.train({"objective": "regression", "verbosity": -1,
                         "linear_tree": True, "linear_lambda": 0.0,
                         "num_leaves": 4},
                        Dataset(X, label=y), num_boost_round=1)
    b_big = lgb.train({"objective": "regression", "verbosity": -1,
                       "linear_tree": True, "linear_lambda": 1e4,
                       "num_leaves": 4},
                      Dataset(X, label=y), num_boost_round=1)

    def max_coef(b):
        mx = 0.0
        for it in b._gbdt.models:
            for t in it:
                for c in t.leaf_coeff:
                    if len(c):
                        mx = max(mx, np.abs(c).max())
        return mx
    assert max_coef(b_big) < max_coef(b_small)


def test_linear_tree_binary_objective(rng):
    X = rng.uniform(-2, 2, (1000, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "linear_tree": True, "num_leaves": 8},
                    Dataset(X, label=y), num_boost_round=10)
    preds = bst.predict(X)
    assert preds[y == 1].mean() > preds[y == 0].mean() + 0.3


def test_linear_tree_refit_and_json_dump(rng):
    X = rng.uniform(-2, 2, (500, 3))
    y = X[:, 0] * 2
    bst = lgb.train({"objective": "regression", "verbosity": -1,
                     "linear_tree": True}, Dataset(X, label=y),
                    num_boost_round=3)
    d = bst.dump_model()
    assert d["tree_info"]
