#!/usr/bin/env python
"""Validate a Chrome trace-event JSON file produced by
``lightgbm_tpu.obs.trace`` (``LGBM_TPU_TRACE=/path.json`` or the
``trace_output`` param).

Checks, in order:
  1. the file is valid JSON;
  2. it is either a bare event list or an object with a
     ``traceEvents`` list (both forms are valid Chrome traces);
  3. every event has the required fields with the right types
     (``name`` str, ``ph`` str, and for complete events ``ph == "X"``:
     numeric non-negative ``ts`` and ``dur``);
  4. metadata events (``ph == "M"``) named ``process_name`` /
     ``thread_name`` / ``process_labels`` carry a dict ``args`` with
     the string payload Perfetto renders (``name`` / ``labels``);
  5. when the trace declares our exporter as producer
     (``otherData.producer == "lightgbm_tpu.obs.trace"``), every pid
     must have a ``process_name`` and every (pid, tid) track with
     complete spans a ``thread_name`` — multi-thread / multi-process
     traces are unreadable pid/tid soup without them;
  6. per (pid, tid) track, ``ts`` is monotonically non-decreasing in
     file order (the exporter sorts by start time; a violation means a
     corrupted or hand-edited trace);
  7. request-scoped serve spans are LINKED: every ``serve/request``
     span carries a non-empty ``args.trace_id`` plus numeric
     ``args.queue_wait_us`` / ``args.device_us`` attribution, every
     ``serve/batch`` span carries ``args.batch_id`` and a non-empty
     ``args.trace_ids`` list, each listed trace_id resolves to a
     request span in the same trace, and each batched request's
     ``args.batch_id`` resolves to a batch span — so a coalesced batch
     shows exactly which requests it carried;
  8. device-lane metadata (obs/profile.py slices merged by
     ``chrome_events``): every complete span on a pid whose
     ``process_name`` contains ``device`` must carry a non-empty
     string ``args.tag`` and an ``args.source`` of ``fallback`` or
     ``profiler`` — the lane is an attribution overlay, and an
     unlabeled slice cannot be joined back to its program tag.

Usage:  python tools/check_trace.py TRACE.json
Exit 0 when the trace is valid; 1 with a diagnostic otherwise — so a
CI or bench run can assert trace integrity with one command.
"""

from __future__ import annotations

import json
import sys
from typing import Any, List, Tuple


def check_trace(path: str) -> Tuple[bool, str]:
    """-> (ok, message). Importable for tests; no side effects."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError as exc:
        return False, f"cannot read {path}: {exc}"
    except json.JSONDecodeError as exc:
        return False, f"{path} is not valid JSON: {exc}"

    our_producer = False
    if isinstance(doc, list):
        events: List[Any] = doc
    elif isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return False, "top-level object has no 'traceEvents' list"
        our_producer = (doc.get("otherData", {}).get("producer")
                        == "lightgbm_tpu.obs.trace")
    else:
        return False, f"unexpected top-level JSON type {type(doc).__name__}"

    _META_PAYLOAD = {"process_name": "name", "thread_name": "name",
                     "process_labels": "labels"}
    # device-lane pids up front (the lane's metadata precedes its spans
    # in our exporter, but a hand-edited trace may reorder them)
    device_pids = {ev.get("pid") for ev in events
                   if isinstance(ev, dict) and ev.get("ph") == "M"
                   and ev.get("name") == "process_name"
                   and isinstance(ev.get("args"), dict)
                   and "device" in str(ev["args"].get("name", "")).lower()}
    n_device = 0
    last_ts = {}  # (pid, tid) -> ts
    named_pids, named_tracks = set(), set()  # from metadata events
    n_complete = n_meta = 0
    req_ids, req_batch_refs = set(), {}  # trace_id set; trace_id->batch_id
    batch_ids, batch_links = set(), []   # batch_id set; (i, trace_ids)
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return False, f"event {i} is not an object"
        name, ph = ev.get("name"), ev.get("ph")
        if not isinstance(name, str) or not name:
            return False, f"event {i} has no string 'name'"
        if not isinstance(ph, str) or not ph:
            return False, f"event {i} ({name!r}) has no string 'ph'"
        if ph == "M" and name in _META_PAYLOAD:
            key = _META_PAYLOAD[name]
            args = ev.get("args")
            if not isinstance(args, dict) or \
                    not isinstance(args.get(key), str) or not args[key]:
                return False, (f"metadata event {i} ({name!r}) lacks a "
                               f"string args.{key}")
            n_meta += 1
            if name == "process_name":
                named_pids.add(ev.get("pid"))
            elif name == "thread_name":
                named_tracks.add((ev.get("pid"), ev.get("tid")))
        if ph != "X":
            continue  # metadata/counter events need no ts ordering
        n_complete += 1
        if ev.get("pid") in device_pids:
            n_device += 1
            args = ev.get("args")
            if not isinstance(args, dict) or \
                    not isinstance(args.get("tag"), str) or \
                    not args.get("tag"):
                return False, (f"device-lane event {i} ({name!r}) lacks "
                               f"a non-empty string args.tag")
            if args.get("source") not in ("fallback", "profiler"):
                return False, (f"device-lane event {i} ({name!r}) has "
                               f"args.source={args.get('source')!r}, not "
                               f"fallback/profiler")
        if name == "serve/request":
            args = ev.get("args")
            if not isinstance(args, dict):
                return False, f"serve/request event {i} has no args dict"
            tid_ = args.get("trace_id")
            if not isinstance(tid_, str) or not tid_:
                return False, (f"serve/request event {i} lacks a "
                               f"non-empty string args.trace_id")
            for key in ("queue_wait_us", "device_us"):
                v = args.get(key)
                if not isinstance(v, (int, float)) or v < 0:
                    return False, (f"serve/request event {i} "
                                   f"(trace_id={tid_}) lacks numeric "
                                   f"args.{key}")
            req_ids.add(tid_)
            if "batch_id" in args:
                req_batch_refs[tid_] = args["batch_id"]
        elif name == "serve/batch":
            args = ev.get("args")
            if not isinstance(args, dict) or "batch_id" not in args:
                return False, (f"serve/batch event {i} lacks "
                               f"args.batch_id")
            ids = args.get("trace_ids")
            if not isinstance(ids, list) or not ids or \
                    not all(isinstance(t, str) and t for t in ids):
                return False, (f"serve/batch event {i} lacks a non-empty "
                               f"args.trace_ids string list")
            batch_ids.add(args["batch_id"])
            batch_links.append((i, ids))
        ts, dur = ev.get("ts"), ev.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0:
            return False, f"event {i} ({name!r}) has invalid ts={ts!r}"
        if not isinstance(dur, (int, float)) or dur < 0:
            return False, f"event {i} ({name!r}) has invalid dur={dur!r}"
        track = (ev.get("pid"), ev.get("tid"))
        prev = last_ts.get(track)
        if prev is not None and ts < prev:
            return False, (f"event {i} ({name!r}) breaks ts monotonicity "
                           f"on track {track}: {ts} < {prev}")
        last_ts[track] = ts
    if our_producer and n_complete:
        for pid, tid in last_ts:
            if pid not in named_pids:
                return False, (f"trace from lightgbm_tpu.obs.trace lacks a "
                               f"process_name metadata event for pid {pid}")
            if (pid, tid) not in named_tracks:
                return False, (f"trace from lightgbm_tpu.obs.trace lacks a "
                               f"thread_name metadata event for track "
                               f"({pid}, {tid})")
    # request<->batch linkage: every id a batch claims must be a request
    # span in this trace, and every batched request's batch must exist
    for i, ids in batch_links:
        missing = [t for t in ids if t not in req_ids]
        if missing:
            return False, (f"serve/batch event {i} references trace_ids "
                           f"{missing} with no matching serve/request span")
    for tid_, bid in req_batch_refs.items():
        if bid not in batch_ids:
            return False, (f"serve/request {tid_} references batch_id "
                           f"{bid!r} with no matching serve/batch span")
    extra = (f", {len(req_ids)} linked request span(s)" if req_ids else "")
    if n_device:
        extra += f", {n_device} device-lane slice(s)"
    return True, (f"ok: {n_complete} complete spans on {len(last_ts)} "
                  f"track(s), {n_meta} metadata event(s){extra}")


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print("usage: python tools/check_trace.py TRACE.json",
              file=sys.stderr)
        return 2
    ok, msg = check_trace(argv[1])
    print(msg, file=sys.stdout if ok else sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
