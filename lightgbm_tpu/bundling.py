"""Exclusive Feature Bundling (EFB) — the wide-sparse data path.

TPU-native re-think of the reference's FeatureGroup/EFB machinery
(ref: src/io/dataset.cpp:112 FindGroups, :251 FastFeatureBundling,
include/LightGBM/feature_group.h:27). The reference bundles mutually
exclusive features so one Bin column stores many features. On TPU the
dense ``[F, N]`` bin tensor is the memory ceiling for wide one-hot data
(10k features x 10M rows = 100 GB unbundled), so bundling compresses
STORAGE to ``[G, N]`` with G = #bundles; histograms are built on the
bundled columns and expanded back to the logical per-feature layout with
a static gather, so the split finder and all tree semantics are
unchanged.

Encoding inside a bundle (ref: feature_group.h bin_offsets_): bundle bin
0 = every member feature at its default bin; member f's non-default bins
``1..nb_f-1`` occupy the half-open range ``[offset_f, offset_f+nb_f-1)``.
The logical bin-0 row of each member's histogram is recovered as
``leaf_total - sum(non-default bins)`` — exact for conflict-free
bundles (and the bundler only merges conflict-free features unless
`max_conflict_rate` allows otherwise, like the reference).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class BundleInfo(NamedTuple):
    """Static bundle structure (host). F = logical used features,
    G = stored columns."""
    bundles: Tuple[Tuple[int, ...], ...]  # member feature idxs per bundle
    group_of: np.ndarray   # [F] int32: stored column of feature f
    offset_of: np.ndarray  # [F] int32: bundle bin of f's logical bin 1
    num_bundle_bins: int   # max bins over stored columns (B_tot)

    @classmethod
    def from_bundles(cls, bundles, num_bins) -> "BundleInfo":
        """Derive the offset layout from bundle membership — the single
        source of truth for the encoding (build + binary reload both
        call this)."""
        f = len(num_bins)
        group_of = np.zeros(f, np.int32)
        offset_of = np.zeros(f, np.int32)
        widths = []
        for g, members in enumerate(bundles):
            off = 1
            for feat in members:
                group_of[feat] = g
                offset_of[feat] = off
                off += int(num_bins[feat]) - 1
            widths.append(off)
        return cls(bundles=tuple(tuple(m) for m in bundles),
                   group_of=group_of, offset_of=offset_of,
                   num_bundle_bins=max(widths) if widths else 1)


def find_bundles(nonzero_masks: np.ndarray, num_bins: np.ndarray,
                 *, max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 256,
                 bundleable: Optional[np.ndarray] = None) -> List[List[int]]:
    """Greedy conflict-bounded grouping (ref: dataset.cpp:112 FindGroups).

    nonzero_masks: [F, S] bool over the binning SAMPLE rows — True where
    the feature is at a non-default bin. Features are scanned in
    decreasing nonzero count (the reference's ordering) and placed into
    the first bundle whose accumulated conflict count and total bin width
    allow it. Features with `bundleable[f] == False` (e.g. default bin
    != 0, which the offset encoding can't represent) are forced into
    singleton bundles — stored verbatim.
    """
    f, s = nonzero_masks.shape
    nz_rows = [np.flatnonzero(nonzero_masks[i]) for i in range(f)]
    return find_bundles_sparse(nz_rows, s, num_bins,
                               max_conflict_rate=max_conflict_rate,
                               max_bundle_bins=max_bundle_bins,
                               bundleable=bundleable)


def find_bundles_sparse(nz_rows: List[np.ndarray], sample_cnt: int,
                        num_bins: np.ndarray,
                        *, max_conflict_rate: float = 0.0,
                        max_bundle_bins: int = 256,
                        bundleable: Optional[np.ndarray] = None
                        ) -> List[List[int]]:
    """Greedy grouping from per-feature non-default sample row INDICES —
    the core shared with the dense path and the entry point for sparse
    (CSC) ingestion, where a dense [F, S] mask would defeat the point.
    Bundle masks stay dense [S] bool (few bundles); each feature costs
    O(nnz_f) to test and place."""
    max_conflicts = int(max_conflict_rate * sample_cnt)
    order = np.argsort(-np.array([len(r) for r in nz_rows], np.int64))
    # cap the per-feature candidate search like the reference's
    # max_search_group (ref: dataset.cpp:118 FindGroups) — without it,
    # wide data where most features conflict degrades quadratically
    max_search = 100
    search_rng = np.random.RandomState(3)

    bundle_members: List[List[int]] = []
    bundle_masks: List[Optional[np.ndarray]] = []
    bundle_conflicts: List[int] = []
    bundle_bins: List[int] = []
    for feat in order:
        feat = int(feat)
        width = int(num_bins[feat]) - 1  # non-default bins it adds
        rows = nz_rows[feat]
        placed = False
        if bundleable is None or bundleable[feat]:
            n_groups = len(bundle_members)
            if n_groups > max_search:
                candidates = search_rng.choice(n_groups, max_search,
                                               replace=False)
            else:
                candidates = range(n_groups)
            for g in candidates:
                if bundle_masks[g] is None:  # singleton-only bundle
                    continue
                if bundle_bins[g] + width + 1 > max_bundle_bins:
                    continue
                conflicts = int(bundle_masks[g][rows].sum())
                if bundle_conflicts[g] + conflicts <= max_conflicts:
                    bundle_members[g].append(feat)
                    bundle_masks[g][rows] = True
                    bundle_conflicts[g] += conflicts
                    bundle_bins[g] += width
                    placed = True
                    break
        if not placed:
            bundle_members.append([feat])
            if bundleable is None or bundleable[feat]:
                mask = np.zeros(sample_cnt, bool)
                mask[rows] = True
                bundle_masks.append(mask)
            else:
                bundle_masks.append(None)
            bundle_conflicts.append(0)
            bundle_bins.append(width + 1)
    return bundle_members


def build_bundled_matrix(bins_fm: np.ndarray, num_bins: np.ndarray,
                         bundles: List[List[int]]
                         ) -> Tuple[np.ndarray, BundleInfo]:
    """Merge a logical [F, N] bin matrix into stored [G, N] columns.

    Rows with several non-default members in one bundle (conflicts, when
    max_conflict_rate > 0) keep the LAST member's code, like the
    reference's push order.
    """
    f, n = bins_fm.shape
    info = BundleInfo.from_bundles(bundles, num_bins)
    dtype = np.uint8 if info.num_bundle_bins <= 256 else np.uint16
    out = np.zeros((len(bundles), n), dtype)
    for g, members in enumerate(bundles):
        col = np.zeros(n, np.int64)
        for feat in members:
            fb = bins_fm[feat].astype(np.int64)
            nz = fb > 0
            col[nz] = info.offset_of[feat] + fb[nz] - 1
        out[g] = col.astype(dtype)
    return out, info


def build_bundled_from_csc(csc, mappers, used: List[int],
                           bundles: List[List[int]],
                           num_bins: np.ndarray
                           ) -> Tuple[np.ndarray, BundleInfo]:
    """Build the stored [G, N] bundle matrix DIRECTLY from a scipy CSC
    matrix — no dense [N, F] or [F, N] intermediate ever exists (the
    point of the sparse ingestion path; ref: sparse_bin.hpp:74 and
    LGBM_DatasetCreateFromCSC c_api.cpp:1330).

    `used[j]` is the raw CSC column of logical feature j; `bundles`
    holds logical feature indices. Encoding identical to
    build_bundled_matrix: member f's non-default bins live at
    [offset_f, offset_f + nb_f - 1); non-bundleable singletons are
    stored verbatim (their implicit zeros at their default bin).
    """
    n = csc.shape[0]
    info = BundleInfo.from_bundles(bundles, num_bins)
    dtype = np.uint8 if info.num_bundle_bins <= 256 else np.uint16
    out = np.zeros((len(bundles), n), dtype)
    col = np.empty(n, np.int64)
    for g, members in enumerate(bundles):
        col[:] = 0
        for feat in members:
            m = mappers[feat]
            # the bin an IMPLICIT zero lands in — transform(0.0), NOT
            # m.default_bin: for categorical mappers category 0's bin is
            # >= 1 while default_bin is always 0
            zb = int(m.transform(np.zeros(1))[0])
            sl = slice(csc.indptr[used[feat]], csc.indptr[used[feat] + 1])
            rows = csc.indices[sl]
            fb = m.transform(csc.data[sl]).astype(np.int64)
            if len(members) == 1 and zb != 0:
                # verbatim singleton: implicit zeros sit at zero's bin
                col[:] = zb
                col[rows] = fb
            elif zb != 0:
                # shared-bundle member whose implicit zeros are a REAL
                # bin (a categorical with category 0 — dense-made
                # bundles can contain these): zeros must be encoded,
                # exactly like the dense builder encodes every fb > 0
                # row. Write the complement first so explicit rows (and
                # later members, last-wins like the reference's push
                # order) overwrite it.
                mask = np.ones(n, bool)
                mask[rows] = False
                col[mask] = info.offset_of[feat] + zb - 1
                nz = fb > 0
                col[rows[nz]] = info.offset_of[feat] + fb[nz] - 1
                col[rows[~nz]] = 0
            else:
                # sparse-made bundles guarantee zb == 0 for shared
                # members, so implicit zeros stay at stored 0
                nz = fb > 0
                col[rows[nz]] = info.offset_of[feat] + fb[nz] - 1
        out[g] = col.astype(dtype)
    return out, info


def should_bundle(bundles: List[List[int]], num_features: int) -> bool:
    """Bundling pays when it actually shrinks the matrix (ref:
    dataset.cpp FastFeatureBundling only groups when beneficial)."""
    return len(bundles) < num_features


# ----------------------------------------------------------------------
# logical views. Device-side decode lives in ops/partition.feature_bins
# (the jit-traced twin of this helper); keep the two in sync.


def decode_stored_host(col_stored: np.ndarray, offset: np.ndarray,
                       width: np.ndarray) -> np.ndarray:
    """Host decode of stored bundle codes to logical bins (vectorized
    over rows with per-row offsets/widths): stored in
    [off, off+width) -> stored - off + 1; else default 0."""
    in_range = (col_stored >= offset) & (col_stored < offset + width)
    return np.where(in_range, col_stored - offset + 1, 0)


def expand_bundle_hist(bundle_hist, group_of, offset_of, nb,
                       max_bins: int, totals):
    """[..., G, B_tot, C] bundled histogram -> [..., F, B, C] logical.

    nb: [F] logical bin counts; totals: [..., C] per-leaf channel totals
    (each feature's default-bin row = total - sum of its own non-default
    bins). Rows b >= nb[f] contain neighboring features' bins — the
    split finder masks them via FeatureMeta.num_bins, and the bin-0
    subtraction here masks them explicitly.
    """
    import jax.numpy as jnp
    b_tot = bundle_hist.shape[-2]
    # gather non-default bins: logical (f, b >= 1) <- bundled
    # (group_of[f], offset_of[f] + b - 1)
    bidx = jnp.arange(max_bins)  # [B]
    src_bin = jnp.clip(offset_of[:, None] + bidx[None, :] - 1, 0, b_tot - 1)
    gathered = bundle_hist[..., group_of, :, :]  # [..., F, B_tot, C]
    idx = jnp.broadcast_to(
        src_bin[..., None],
        gathered.shape[:-2] + (max_bins, gathered.shape[-1]))
    hist = jnp.take_along_axis(gathered, idx, axis=-2)  # [..., F, B, C]
    own = (bidx[None, :] >= 1) & (bidx[None, :] < nb[:, None])  # [F, B]
    nondefault = jnp.sum(hist * own[..., None], axis=-2)  # [..., F, C]
    default_row = totals[..., None, :] - nondefault
    hist = hist.at[..., 0, :].set(default_row)
    return hist
