"""tools/check_perf_gate.py — the CI perf-regression gate over the
BENCH_*.json trajectory and the histogram traffic-model floor
(ISSUE 7 satellite; ROADMAP item 4's driver-visible-proof debt)."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_perf_gate  # noqa: E402


def test_gate_passes_on_repo_state(capsys):
    assert check_perf_gate.main([]) == 0
    out = capsys.readouterr().out
    assert "perf gate OK" in out
    assert "13-pass schedule" in out


def test_gate_reduction_floor_is_acceptance_number():
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    assert floor["hist"]["min_bytes_reduction"] >= 1.8


def test_gate_fails_on_traffic_regression(tmp_path, capsys):
    """A candidate whose own hist_bytes_reduction fell below the floor
    (scheduler/encoding regression) must fail the gate — the ratio is
    N-invariant, so it works for shrunken relay-fallback runs too."""
    fat = {"metric": "boosting_iters_per_sec_higgs_shape",
           "value": 1.0, "vs_baseline": 1.0,
           "unit": "iters/sec (platform=cpu)",
           "hist_bytes_per_iter": int(12e9),
           "hist_bytes_reduction": 1.0}
    cand = tmp_path / "BENCH_candidate.json"
    cand.write_text(json.dumps(fat))
    assert check_perf_gate.main([str(cand)]) == 1
    assert "hist_bytes_reduction" in capsys.readouterr().out


def test_gate_accepts_unpacked_train_config_candidate(tmp_path):
    """The standard 63-bin train bench (no packing, ~1.35x reduction,
    bytes far above the packed fixture floor) must PASS: absolute bytes
    are not comparable across configs/row counts, only the ratio is."""
    ok = {"metric": "boosting_iters_per_sec_higgs_shape",
          "value": 50.0, "vs_baseline": 13.0,
          "unit": "iters/sec (N=10500000)",
          "hist_bytes_per_iter": int(6.0e9),
          "hist_bytes_reduction": 1.35}
    cand = tmp_path / "BENCH_candidate.json"
    cand.write_text(json.dumps(ok))
    assert check_perf_gate.main([str(cand)]) == 0


def test_gate_fails_on_throughput_drop(tmp_path, capsys):
    """A candidate >10% below the recorded same-platform floor fails."""
    lines = check_perf_gate._load_bench_lines()
    if not lines:
        pytest.skip("no recorded BENCH trajectory")
    cpu = [r for _, r in lines
           if check_perf_gate._platform_of(r.get("unit", "")) == "cpu"]
    if not cpu:
        pytest.skip("no cpu BENCH lines recorded")
    floor_v = max(r.get("vs_baseline", 0.0) for r in cpu)
    slow = {"metric": "boosting_iters_per_sec_higgs_shape",
            "value": 0.01, "vs_baseline": floor_v * 0.5,
            "unit": "iters/sec (platform=cpu)"}
    cand = tmp_path / "BENCH_candidate.json"
    cand.write_text(json.dumps(slow))
    assert check_perf_gate.main([str(cand)]) == 1
    assert "dropped" in capsys.readouterr().out


def test_gate_includes_memory_ceiling(capsys):
    """The gate now recomputes the analytic peak-memory model against
    the recorded ceiling (ISSUE 8) — its line must appear in a passing
    run, and the floor file must carry the memory section."""
    assert check_perf_gate.main([]) == 0
    assert "memory model" in capsys.readouterr().out
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    assert floor["memory"]["max_peak_model_bytes"] > 0
    assert floor["memory"]["model_vs_measured_band"] == 1.5


def test_phase_trajectory_flags_regression():
    """A phase that blew past its recorded floor fails; phases below
    the absolute-noise floor are ignored."""
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    lines = [
        ("BENCH_a.json", {"unit": "iters/sec (platform=cpu)",
                          "phases": {"train/iteration": 1.0,
                                     "tiny": 0.01}}),
        ("BENCH_b.json", {"unit": "iters/sec (platform=cpu)",
                          "phases": {"train/iteration": 2.0,
                                     "tiny": 0.09}}),
    ]
    failures = []
    check_perf_gate.check_phase_trajectory(floor, failures, lines)
    assert len(failures) == 1 and "train/iteration" in failures[0]

    ok_lines = [
        ("BENCH_a.json", {"unit": "iters/sec (platform=cpu)",
                          "phases": {"train/iteration": 1.0}}),
        ("BENCH_b.json", {"unit": "iters/sec (platform=cpu)",
                          "phases": {"train/iteration": 1.2}}),
    ]
    failures = []
    check_perf_gate.check_phase_trajectory(floor, failures, ok_lines)
    assert failures == []


def test_phase_trajectory_skips_without_summaries(capsys):
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    failures = []
    check_perf_gate.check_phase_trajectory(
        floor, failures, [("BENCH_a.json", {"unit": "iters/sec"})])
    assert failures == []
    assert "skipped" in capsys.readouterr().out


def test_gate_parses_driver_wrapper_shape():
    """The driver stores bench output as {"n","cmd","rc","tail"}; the
    gate must dig the contract line out of `tail`."""
    rec = check_perf_gate._extract_metric_record({
        "n": 9, "rc": 0,
        "tail": 'noise\n{"metric": "boosting_iters_per_sec_higgs_shape", '
                '"value": 1.5, "vs_baseline": 0.39, "unit": "iters/sec"}\n'})
    assert rec is not None and rec["vs_baseline"] == 0.39
    assert check_perf_gate._extract_metric_record({"tail": "junk"}) is None


def test_xla_cross_check_runs_and_agrees(capsys):
    """Check 5 (ISSUE 9): the compiled packed+int8 wave kernel's
    argument bytes agree with the analytic traffic model per-pass
    within the declared band, and the memory model's operand/slab
    components cover the executable's buffers — on the CPU backend the
    check must RUN (not skip)."""
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    assert floor["xla"]["arg_bytes_band"] >= 1.0
    failures = []
    check_perf_gate.check_xla_cost_model(floor, failures)
    out = capsys.readouterr().out
    assert failures == []
    assert "xla vs traffic model" in out
    assert "xla vs memory model" in out
    assert "skipped" not in out


def test_xla_cross_check_flags_model_divergence(capsys):
    """A traffic model that diverged from what XLA streams must fail
    the band: simulate by shrinking the declared band to ~0."""
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    floor["xla"] = dict(floor["xla"], arg_bytes_band=1.0000001,
                        min_bytes_accessed_ratio=1e9)
    failures = []
    check_perf_gate.check_xla_cost_model(floor, failures)
    # the tight band trips at least the bytes-accessed ratio check
    assert any("xla cross-check" in f for f in failures)


def test_xla_cross_check_skips_gracefully(capsys, monkeypatch):
    """No cost analysis on the backend => skip, never fail (the TPU
    relay path can't be probed from CI)."""
    import lightgbm_tpu.obs.xla as obs_xla
    monkeypatch.setattr(obs_xla, "aot_cost_summary",
                        lambda *a, **k: None)
    with open(check_perf_gate.FLOOR_PATH) as fh:
        floor = json.load(fh)
    failures = []
    check_perf_gate.check_xla_cost_model(floor, failures)
    assert failures == []
    assert "skipped" in capsys.readouterr().out

    # a missing floor section also skips
    failures = []
    check_perf_gate.check_xla_cost_model({}, failures)
    assert failures == []
