"""Serving fleet (ISSUE 17, serve half): FleetRouter over N replicas.

- predict parity: the fleet answer is bit-identical to a direct
  single-server predict (the pack contract that makes failover and
  hedging safe).
- failover: a dead replica (injected ``fail_dispatch``) never loses a
  request; the dispatch faults feed the quarantine state machine and
  the per-replica breaker, and the probe loop reinstates the replica
  when it comes back.
- hedged dispatch fires on a slow primary and the winning answer keeps
  parity; divergent answers trip the asserted parity contract.
- drain: a draining fleet sheds new requests with retry-after and
  flushes in-flight work.
- observability: per-replica up/quarantined gauges render in the real
  OpenMetrics document, fleet counters accrue, and replica scrapes
  aggregate into fleet-wide totals.
- tools/check_fleet.py (the subprocess SIGKILL/SIGSTOP/SIGTERM chaos
  validator) and check_perf_gate.py check 12 (availability floor).
"""

import asyncio
import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.obs.export import render_openmetrics
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.resilience.errors import ServerOverloaded
from lightgbm_tpu.serve import (FleetRouter, InProcessReplica,
                                ModelRegistry, ModelServer,
                                aggregate_counter_totals,
                                build_inprocess_fleet)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _data(n=300, f=6, seed=5):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * r.randn(n) > 0.4)
    return X, y.astype(np.float32)


def _booster():
    X, y = _data()
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, y),
                    num_boost_round=3)
    return bst, X


def _replica(name, bst, **server_kw):
    registry = ModelRegistry()
    registry.load("m", booster=bst)
    return InProcessReplica(name, ModelServer(registry, **server_kw))


def _fleet(bst, n=3, **kw):
    kw.setdefault("probe_interval_ms", 10.0)
    kw.setdefault("breaker_reset_s", 0.2)
    return FleetRouter([_replica(f"r{i}", bst) for i in range(n)], **kw)


async def _closed(fleet):
    fleet.stop()
    for rep in fleet.replicas:
        await rep.server.close()


class TestRouting:
    def test_fleet_predict_bit_identical_to_direct(self):
        bst, X = _booster()
        fleet = _fleet(bst)
        direct = fleet.replicas[0].server.registry.get("m") \
            .model.predict(X[:32])

        async def run():
            out = await fleet.predict("m", X[:32])
            assert np.array_equal(np.asarray(out), np.asarray(direct))
            await _closed(fleet)

        asyncio.run(run())

    def test_failover_loses_nothing_and_quarantines(self):
        bst, X = _booster()
        # long breaker reset so the opened breaker is still observable
        # after the load finishes
        fleet = _fleet(bst, breaker_reset_s=60.0)
        expect = fleet.replicas[0].server.registry.get("m") \
            .model.predict(X[:8])
        failovers0 = global_metrics.counters.get("fleet/failovers", 0)

        async def run():
            fleet.replicas[0].fail_dispatch = True
            # round-robin sends ~1/3 of these to r0 first: enough
            # failures to trip its breaker (threshold 5)
            outs = await asyncio.gather(
                *[fleet.predict("m", X[:8]) for _ in range(24)])
            for out in outs:
                assert np.array_equal(np.asarray(out),
                                      np.asarray(expect))
            await _closed(fleet)

        asyncio.run(run())
        assert global_metrics.counters["fleet/failovers"] > failovers0
        # dispatch faults fed the probe state machine; two sweeps
        # formalize the quarantine
        fleet.probe_once()
        fleet.probe_once()
        st = fleet.stats()["replicas"]["r0"]
        assert st["quarantined"] and not st["up"]
        assert fleet._state["r0"].breaker.is_open
        names = [r.name for r in fleet.healthy_replicas()]
        assert names == ["r1", "r2"]

    def test_reinstate_after_recovery(self):
        bst, X = _booster()
        fleet = _fleet(bst)
        fleet.replicas[0].fail_dispatch = True
        fleet.probe_once()
        fleet.probe_once()
        assert fleet.stats()["replicas"]["r0"]["quarantined"]
        reinstates0 = global_metrics.counters.get("fleet/reinstates", 0)
        fleet.replicas[0].fail_dispatch = False
        fleet.probe_once()
        fleet.probe_once()
        assert not fleet.stats()["replicas"]["r0"]["quarantined"]
        assert global_metrics.counters["fleet/reinstates"] \
            == reinstates0 + 1
        asyncio.run(_closed(fleet))

    def test_whole_fleet_down_sheds_with_retry_after(self):
        bst, X = _booster()
        fleet = _fleet(bst, n=2)
        for rep in fleet.replicas:
            rep.fail_dispatch = True
        fleet.probe_once()
        fleet.probe_once()

        async def run():
            with pytest.raises(ServerOverloaded) as ei:
                await fleet.predict("m", X[:4])
            assert ei.value.retry_after_s > 0
            await _closed(fleet)

        asyncio.run(run())

    def test_constructor_validation(self):
        bst, _ = _booster()
        with pytest.raises(ValueError, match="at least one"):
            FleetRouter([])
        reps = [_replica("dup", bst), _replica("dup", bst)]
        with pytest.raises(ValueError, match="duplicate"):
            FleetRouter(reps)

    def test_build_inprocess_fleet_from_model_string(self):
        bst, X = _booster()
        cfg = Config.from_params({"serve_fleet_replicas": 2,
                                  "verbosity": -1})
        fleet = build_inprocess_fleet(bst.model_to_string(), cfg)
        assert len(fleet.replicas) == 2
        direct = bst.predict(X[:8])

        async def run():
            out = await fleet.predict("default", X[:8])
            assert np.array_equal(np.asarray(out), np.asarray(direct))
            await _closed(fleet)

        asyncio.run(run())


class TestHedging:
    def test_hedge_fires_on_slow_primary_and_keeps_parity(self):
        bst, X = _booster()

        class SlowReplica(InProcessReplica):
            async def predict(self, name, x, raw_score=False):
                await asyncio.sleep(0.25)
                return await super().predict(name, x,
                                             raw_score=raw_score)

        registry = ModelRegistry()
        registry.load("m", booster=bst)
        slow = SlowReplica("slow", ModelServer(registry))
        fast = _replica("fast", bst)
        # max_attempts=1: the answer must come from the HEDGE, not a
        # failover retry
        fleet = FleetRouter([slow, fast], hedge_ms=20.0,
                            probe_interval_ms=10.0, max_attempts=1)
        hedges0 = global_metrics.counters.get("fleet/hedges", 0)
        expect = fast.server.registry.get("m").model.predict(X[:8])

        async def run():
            # pin the round-robin cursor so the slow replica is primary
            while next(fleet._rr) % 2 != 1:
                pass
            out = await fleet.predict("m", X[:8])
            assert np.array_equal(np.asarray(out), np.asarray(expect))
            await asyncio.sleep(0.3)  # let the loser finish its parity
            await _closed(fleet)

        asyncio.run(run())
        assert global_metrics.counters["fleet/hedges"] == hedges0 + 1

    def test_parity_violation_is_loud(self):
        bst, _ = _booster()
        fleet = _fleet(bst, n=2)
        violations0 = global_metrics.counters.get(
            "fleet/parity_violations", 0)
        with pytest.raises(AssertionError, match="different bits"):
            fleet._assert_parity(np.zeros(3), np.ones(3))
        assert global_metrics.counters["fleet/parity_violations"] \
            == violations0 + 1
        asyncio.run(_closed(fleet))


class TestDrain:
    def test_drain_sheds_new_and_flushes_inflight(self):
        bst, X = _booster()
        fleet = _fleet(bst)

        async def run():
            first = asyncio.ensure_future(fleet.predict("m", X[:16]))
            await asyncio.sleep(0)
            fleet.begin_drain()
            with pytest.raises(ServerOverloaded, match="draining"):
                await fleet.predict("m", X[:4])
            assert await fleet.drain(timeout_s=10.0)
            # the in-flight request was served, not dropped
            out = await first
            assert out.shape == (16,)
            for rep in fleet.replicas:
                await rep.server.close()

        asyncio.run(run())


class TestObservability:
    def test_replica_gauges_render_in_openmetrics(self):
        bst, _ = _booster()
        fleet = _fleet(bst)
        fleet.replicas[1].fail_dispatch = True
        fleet.probe_once()
        fleet.probe_once()
        text = render_openmetrics()
        assert "lgbmtpu_fleet_replicas 3" in text
        assert 'lgbmtpu_fleet_replica_up{replica="r0"} 1' in text
        assert 'lgbmtpu_fleet_replica_up{replica="r1"} 0' in text
        assert ('lgbmtpu_fleet_replica_quarantined{replica="r1"} 1'
                in text)
        assert ('lgbmtpu_fleet_replica_quarantined{replica="r2"} 0'
                in text)
        asyncio.run(_closed(fleet))

    def test_scrapes_aggregate_to_fleet_totals(self):
        bst, X = _booster()
        fleet = _fleet(bst, n=2)

        async def run():
            for _ in range(4):
                await fleet.predict("m", X[:4])
            await _closed(fleet)

        asyncio.run(run())
        totals = aggregate_counter_totals(fleet.scrape_replicas())
        assert totals.get("lgbmtpu_serve_requests_total", 0) >= 4
        assert totals.get("lgbmtpu_fleet_requests_total", 0) >= 4

    def test_aggregate_counter_totals_pure_text(self):
        totals = aggregate_counter_totals({
            "a": "# HELP x_total c\nx_total 2\ny_gauge 9\n",
            "b": 'x_total{replica="b"} 3\nz_total 1.5\n',
        })
        assert totals == {"x_total": 5.0, "z_total": 1.5}

    def test_fleet_metrics_endpoint_ready_tracks_rotation(self):
        import urllib.request
        bst, _ = _booster()
        fleet = _fleet(bst, n=2)
        ep = fleet.start_metrics_endpoint(0)

        def get(path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{ep.port}{path}",
                        timeout=5) as resp:
                    return resp.status
            except urllib.error.HTTPError as exc:
                return exc.code

        assert get("/readyz") == 200
        for rep in fleet.replicas:
            rep.fail_dispatch = True
        fleet.probe_once()
        fleet.probe_once()
        assert get("/readyz") == 503
        asyncio.run(_closed(fleet))


class TestToolsWiring:
    @pytest.mark.slow
    def test_check_fleet_tool(self):
        """The subprocess chaos validator passes in-process (quick-tier
        wiring, same idiom as check_resilience): SIGKILL under load
        with zero lost requests, SIGSTOP/SIGCONT quarantine cycle,
        scrape aggregation, SIGTERM exit-75 drain."""
        import check_fleet
        assert check_fleet.main() == 0

    def test_perf_gate_check12_skips_without_fleet_bench(self, capsys,
                                                         tmp_path):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        assert floor["fleet"]["min_availability"] >= 0.999
        failures = []
        check_perf_gate.check_fleet_availability(
            floor, failures, str(tmp_path / "absent.json"))
        assert failures == []
        assert "skipped" in capsys.readouterr().out

    def test_perf_gate_check12_flags_lost_requests(self, tmp_path):
        import check_perf_gate
        with open(check_perf_gate.FLOOR_PATH) as fh:
            floor = json.load(fh)
        bad = {"metric": "fleet_availability", "value": 0.9,
               "fleet": {"availability": 0.9, "requests": 100,
                         "served": 90, "failed": 10, "failovers": 2,
                         "quarantines": 1, "killed_quarantined": False,
                         "parity_ok": False}}
        p = tmp_path / "cand.json"
        p.write_text(json.dumps(bad))
        failures = []
        check_perf_gate.check_fleet_availability(floor, failures,
                                                 str(p))
        assert len(failures) == 3
        assert "availability" in failures[0]
        assert "bitwise" in failures[1]
        assert "quarantined" in failures[2]

        ok = dict(bad, value=1.0,
                  fleet=dict(bad["fleet"], availability=1.0,
                             served=100, failed=0,
                             killed_quarantined=True, parity_ok=True))
        p.write_text(json.dumps(ok))
        failures = []
        check_perf_gate.check_fleet_availability(floor, failures,
                                                 str(p))
        assert failures == []
