"""C-ABI shim tests: drive the framework through lib_lightgbm_tpu.so the
way reference harnesses drive lib_lightgbm.so (ref: include/LightGBM/
c_api.h; tests/c_api_test/test_.py is the reference's ctypes smoke test).

Two tiers: ctypes from this process (cheap), and a genuinely external C
program that embeds the interpreter through the shim (the third-party
tooling path)."""

import ctypes
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from conftest import make_binary

REPO = Path(__file__).resolve().parent.parent
SO_PATH = REPO / "lightgbm_tpu" / "lib_lightgbm_tpu.so"


def _ensure_built():
    if not SO_PATH.exists():
        subprocess.run(["make", "-C", str(REPO / "native"), "capi"],
                       check=True, capture_output=True)
    return SO_PATH


@pytest.fixture(scope="module")
def lib():
    lib = ctypes.CDLL(str(_ensure_built()))
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


class TestCApiInProcess:
    def test_dataset_booster_lifecycle(self, lib):
        X, y = make_binary(500, 6)
        X64 = np.ascontiguousarray(X, np.float64)
        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X64.ctypes.data_as(ctypes.c_void_p), 1,  # C_API_DTYPE_FLOAT64
            ctypes.c_int32(X64.shape[0]), ctypes.c_int32(X64.shape[1]),
            1, b"max_bin=63", None, ctypes.byref(ds)))
        y32 = np.ascontiguousarray(y, np.float32)
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y32.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y32)), 0))  # C_API_DTYPE_FLOAT32

        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 500
        _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(n)))
        assert n.value == 6

        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"metric=auc verbosity=-1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(10):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        it = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst,
                                                        ctypes.byref(it)))
        assert it.value == 10

        # train AUC via GetEval(data_idx=0)
        cnt = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)))
        assert cnt.value >= 1
        res = (ctypes.c_double * cnt.value)()
        out_len = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEval(bst, 0, ctypes.byref(out_len),
                                            res))
        assert out_len.value == cnt.value
        assert res[0] > 0.8  # AUC on train

        # predict (normal = probability)
        out = (ctypes.c_double * 500)()
        out_len64 = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(500), ctypes.c_int32(6), 1, 0, 0, -1, b"",
            ctypes.byref(out_len64), out))
        assert out_len64.value == 500
        pred = np.asarray(out[:500])
        assert 0.0 <= pred.min() and pred.max() <= 1.0
        auc_gap = pred[y > 0.5].mean() - pred[y <= 0.5].mean()
        assert auc_gap > 0.2

        # save -> load -> identical raw predictions
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "model.txt")
            _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0,
                                                  path.encode()))
            loaded = ctypes.c_void_p()
            iters = ctypes.c_int()
            _check(lib, lib.LGBM_BoosterCreateFromModelfile(
                path.encode(), ctypes.byref(iters), ctypes.byref(loaded)))
            assert iters.value == 10
            out2 = (ctypes.c_double * 500)()
            _check(lib, lib.LGBM_BoosterPredictForMat(
                loaded, X64.ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int32(500), ctypes.c_int32(6), 1, 1, 0, -1, b"",
                ctypes.byref(out_len64), out2))
            out1 = (ctypes.c_double * 500)()
            _check(lib, lib.LGBM_BoosterPredictForMat(
                bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
                ctypes.c_int32(500), ctypes.c_int32(6), 1, 1, 0, -1, b"",
                ctypes.byref(out_len64), out1))
            np.testing.assert_allclose(np.asarray(out2[:500]),
                                       np.asarray(out1[:500]),
                                       rtol=1e-5, atol=1e-6)
            _check(lib, lib.LGBM_BoosterFree(loaded))

        # model string
        buf_len = 1 << 20
        buf = ctypes.create_string_buffer(buf_len)
        str_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterSaveModelToString(
            bst, 0, -1, 0, ctypes.c_int64(buf_len), ctypes.byref(str_len),
            buf))
        assert 0 < str_len.value <= buf_len
        assert buf.value.decode().startswith("tree")

        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_csr_dataset_and_predict(self, lib):
        """CSR creation + prediction through the C ABI (ref:
        LGBM_DatasetCreateFromCSR c_api.cpp:1311) must match the dense
        path on the same data."""
        from scipy import sparse
        rng = np.random.RandomState(5)
        X = rng.randn(400, 8)
        X[rng.rand(400, 8) < 0.6] = 0.0  # sparse-ish
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        csr = sparse.csr_matrix(X)
        indptr = np.ascontiguousarray(csr.indptr, np.int32)
        indices = np.ascontiguousarray(csr.indices, np.int32)
        vals = np.ascontiguousarray(csr.data, np.float64)

        ds = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromCSR(
            indptr.ctypes.data_as(ctypes.c_void_p), 2,  # INT32
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,  # FLOAT64
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(8), b"max_bin=63", None, ctypes.byref(ds)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(n)))
        assert n.value == 400
        _check(lib, lib.LGBM_DatasetSetField(
            ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(400), 0))
        bst = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            ds, b"objective=binary num_leaves=15 min_data_in_leaf=5 "
                b"verbosity=-1", ctypes.byref(bst)))
        fin = ctypes.c_int()
        for _ in range(8):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bst,
                                                      ctypes.byref(fin)))
        out_csr = (ctypes.c_double * 400)()
        out_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSR(
            bst, indptr.ctypes.data_as(ctypes.c_void_p), 2,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(8), 1, 0, -1, b"",
            ctypes.byref(out_len), out_csr))
        assert out_len.value == 400
        X64 = np.ascontiguousarray(X, np.float64)
        out_dense = (ctypes.c_double * 400)()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bst, X64.ctypes.data_as(ctypes.c_void_p), 1,
            ctypes.c_int32(400), ctypes.c_int32(8), 1, 1, 0, -1, b"",
            ctypes.byref(out_len), out_dense))
        np.testing.assert_allclose(np.asarray(out_csr[:400]),
                                   np.asarray(out_dense[:400]),
                                   rtol=1e-6, atol=1e-7)
        _check(lib, lib.LGBM_BoosterFree(bst))
        _check(lib, lib.LGBM_DatasetFree(ds))

    def test_error_reporting(self, lib):
        bst = ctypes.c_void_p(0)
        fin = ctypes.c_int()
        rc = lib.LGBM_BoosterUpdateOneIter(
            ctypes.c_void_p(999999), ctypes.byref(fin))
        assert rc != 0
        assert b"invalid handle" in lib.LGBM_GetLastError()


C_DRIVER = r"""
#include <stdio.h>
#include <stdlib.h>

typedef void* H;
extern int LGBM_DatasetCreateFromMat(const void*, int, int, int, int,
                                     const char*, H, H*);
extern int LGBM_DatasetSetField(H, const char*, const void*, int, int);
extern int LGBM_BoosterCreate(H, const char*, H*);
extern int LGBM_BoosterUpdateOneIter(H, int*);
extern int LGBM_BoosterPredictForMat(H, const void*, int, int, int, int,
                                     int, int, int, const char*,
                                     long long*, double*);
extern int LGBM_BoosterFree(H);
extern int LGBM_DatasetFree(H);
extern const char* LGBM_GetLastError(void);

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL: %s\n", LGBM_GetLastError()); return 1; }

int main(void) {
  enum { N = 200, F = 4 };
  static double data[N * F];
  static float label[N];
  unsigned s = 42;
  for (int i = 0; i < N; ++i) {
    double t = 0;
    for (int j = 0; j < F; ++j) {
      s = s * 1103515245u + 12345u;
      data[i * F + j] = ((double)(s >> 16 & 0x7fff) / 16384.0) - 1.0;
      t += data[i * F + j];
    }
    label[i] = t > 0 ? 1.0f : 0.0f;
  }
  H ds = NULL, bst = NULL;
  CHECK(LGBM_DatasetCreateFromMat(data, 1, N, F, 1, "max_bin=31", NULL,
                                  &ds));
  CHECK(LGBM_DatasetSetField(ds, "label", label, N, 0));
  CHECK(LGBM_BoosterCreate(ds,
      "objective=binary num_leaves=7 min_data_in_leaf=5 verbosity=-1",
      &bst));
  int fin = 0;
  for (int i = 0; i < 5; ++i) CHECK(LGBM_BoosterUpdateOneIter(bst, &fin));
  static double out[N];
  long long out_len = 0;
  CHECK(LGBM_BoosterPredictForMat(bst, data, 1, N, F, 1, 0, 0, -1, "",
                                  &out_len, out));
  if (out_len != N) { fprintf(stderr, "bad out_len\n"); return 1; }
  double pos = 0, neg = 0; int np_ = 0, nn = 0;
  for (int i = 0; i < N; ++i) {
    if (label[i] > 0.5) { pos += out[i]; ++np_; } else { neg += out[i]; ++nn; }
  }
  if (pos / np_ <= neg / nn) { fprintf(stderr, "no signal\n"); return 1; }
  CHECK(LGBM_BoosterFree(bst));
  CHECK(LGBM_DatasetFree(ds));
  printf("C-API-OK\n");
  return 0;
}
"""


@pytest.mark.slow
def test_capi_external_c_program(tmp_path):
    """A plain C program (no Python involved on its side) trains and
    predicts through the shim — the reference's external-tooling
    contract."""
    _ensure_built()
    src = tmp_path / "driver.c"
    src.write_text(C_DRIVER)
    exe = tmp_path / "driver"
    subprocess.run(
        ["g++", "-x", "c", str(src), "-x", "none", "-o", str(exe),
         str(SO_PATH), f"-Wl,-rpath,{SO_PATH.parent}"],
        check=True, capture_output=True)
    from lightgbm_tpu.hostenv import cpu_child_env
    env = cpu_child_env()
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([str(exe)], env=env, capture_output=True,
                          text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert "C-API-OK" in proc.stdout
