"""Bit-packed bin storage, the fused gradient/histogram wave, the
subtraction-aware wave schedule, and deterministic histogram
accumulation (ISSUE 7 / ROADMAP item 3).

Parity strategy: the packed layout and the fused-gradient kernel are
pure re-encodings — same values, same accumulation order — so packed
vs unpacked (and fused vs pre-built ghT) must agree BITWISE, end to
end through training, on the quantized fixture and on float data
alike. The no-subtraction oracle reorders f32 accumulation, so its
gate is tolerance-based (documented in config.tpu_wave_subtract).
"""

import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.bin_pack import (PACK_ALIGN, PackedBins,
                                       pack_bins_host, pack_vpb,
                                       to_device, unpack_bins,
                                       unpack_feature, unpack_rows)


def strip_params(model_str: str) -> str:
    """Model string minus the echoed parameters block — knob values
    legitimately differ between the compared configs; everything else
    (trees, thresholds, leaf values) must match exactly."""
    out, skip = [], False
    for line in model_str.splitlines():
        if line.startswith("parameters:"):
            skip = True
        elif skip and line.startswith("end of parameters"):
            skip = False
            continue
        if not skip:
            out.append(line)
    return "\n".join(out)


def _binary(n=3000, f=8, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logit = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.3 * X[:, 2] * X[:, 3]
    y = (logit + 0.2 * r.randn(n) > 0.5).astype(np.float32)
    return X, y


BASE = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
        "min_data_in_leaf": 5, "verbosity": -1, "max_bin": 15}


def _train(X, y, rounds=5, **extra):
    return lgb.train({**BASE, **extra}, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


# ---------------------------------------------------------------------------
# pack/unpack roundtrip property (satellite: max_bin in {2,15,16,63,255})
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("max_bins", [2, 15, 16, 63, 255])
@pytest.mark.parametrize("n", [1, 700, 2048, 4097])
def test_pack_roundtrip(max_bins, n):
    r = np.random.RandomState(max_bins + n)
    f = 5
    bins = r.randint(0, max_bins, (f, n)).astype(np.uint8)
    pb = pack_bins_host(bins, max_bins)
    if max_bins > 15:
        assert pb is None and pack_vpb(max_bins) == 1
        return
    assert pb.vpb == (4 if max_bins <= 3 else 2)
    assert pb.section % PACK_ALIGN == 0
    assert pb.shape == (f, n)
    # packed bytes are the point: <= ceil(N/vpb) per feature (padded)
    assert pb.nbytes <= f * (pb.section)
    dev = to_device(pb)
    np.testing.assert_array_equal(np.asarray(unpack_bins(dev)), bins)
    # gathered per-row unpack (the partition path)
    feat = r.randint(0, f, n).astype(np.int32)
    rows = np.arange(n)
    np.testing.assert_array_equal(
        np.asarray(unpack_rows(dev, jnp.asarray(feat), jnp.asarray(rows))),
        bins[feat, rows])
    np.testing.assert_array_equal(np.asarray(unpack_feature(dev, 0)),
                                  bins[0])


# ---------------------------------------------------------------------------
# kernel bit-parity: packed vs unpacked on the quantized (integer) fixture
# ---------------------------------------------------------------------------
def _quant_fixture(n=3000, f=7, b=15, seed=3):
    r = np.random.RandomState(seed)
    bins = r.randint(0, b, (f, n)).astype(np.uint8)
    mask = (r.rand(n) < 0.8).astype(np.int8)
    g_int = (r.randint(-3, 4, n) * mask).astype(np.int8)
    h_int = (r.randint(0, 5, n) * mask).astype(np.int8)
    row_leaf = r.randint(0, 6, n).astype(np.int32)
    return bins, g_int, h_int, mask, row_leaf


def test_packed_hist_bit_parity_quantized():
    from lightgbm_tpu.ops.pallas_histogram import (
        hist_multi_xla, hist_multi_int8_xla, hist_pallas_multi,
        hist_pallas_multi_int8, hist_pallas)
    b, slots = 15, 42
    bins, g_int, h_int, mask, row_leaf = _quant_fixture(b=b)
    pb = to_device(pack_bins_host(bins, b))
    rl = jnp.asarray(row_leaf)
    ids = jnp.asarray([0, 2, 5, 1] + [-2] * (slots - 4), jnp.int32)
    ghT = jnp.asarray(np.stack([g_int, h_int, mask], axis=1), jnp.float32)
    ref = hist_multi_xla(jnp.asarray(bins), ghT, rl, ids,
                         max_bins=b, num_slots=slots)
    # f32 multi kernel, packed: exact integer sums -> bitwise
    pal = hist_pallas_multi(pb, ghT, rl, ids, max_bins=b, num_slots=slots,
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(pal), np.asarray(ref))
    # int8 kernel: packed pallas == unpacked pallas == XLA int32 twin
    ghT_i8 = jnp.asarray(np.stack([g_int, h_int, mask], axis=1), jnp.int8)
    ref_i = hist_multi_int8_xla(jnp.asarray(bins), ghT_i8, rl, ids,
                                max_bins=b, num_slots=slots)
    for bins_arg in (pb, jnp.asarray(bins)):
        pal_i = hist_pallas_multi_int8(bins_arg, ghT_i8, rl, ids,
                                       max_bins=b, num_slots=slots,
                                       interpret=True)
        assert pal_i.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(pal_i), np.asarray(ref_i))
    # single-leaf kernel + XLA build path on PackedBins
    from lightgbm_tpu.ops.histogram import build_histogram
    g = jnp.asarray(g_int, jnp.float32)
    h = jnp.asarray(h_int, jnp.float32)
    m = jnp.asarray(mask, jnp.float32)
    ref_s = build_histogram(jnp.asarray(bins), g, h, m, max_bins=b,
                            impl="xla")
    np.testing.assert_array_equal(
        np.asarray(build_histogram(pb, g, h, m, max_bins=b, impl="xla")),
        np.asarray(ref_s))
    gh3 = jnp.stack([g * m, h * m, m]).astype(jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(hist_pallas(pb, gh3, max_bins=b, interpret=True)),
        np.asarray(ref_s))


def test_packed_partition_bit_parity():
    """feature_bins / apply_split / apply_wave_splits on PackedBins must
    reproduce the dense uint8 layout exactly (incl. categorical bitsets,
    NaN default-left routing, invalid wave entries)."""
    from lightgbm_tpu.ops import partition as part_ops
    rng = np.random.RandomState(0)
    N, F, B, L, W = 500, 6, 15, 15, 5
    for trial in range(4):
        bins = rng.randint(0, B, (F, N)).astype(np.uint8)
        pb = to_device(pack_bins_host(bins, B))
        row_leaf = rng.randint(0, 8, N).astype(np.int32)
        leaves = rng.permutation(8)[:W].astype(np.int32)
        rights = (8 + np.arange(W)).astype(np.int32)
        feats = rng.randint(0, F, W).astype(np.int32)
        thrs = rng.randint(0, B - 1, W).astype(np.int32)
        dlefts = rng.rand(W) > 0.5
        cmasks = rng.rand(W, B) > 0.5
        valid = np.ones(W, bool)
        valid[-1] = False
        num_bins = np.full(F, B, np.int32)
        missing = rng.randint(0, 3, F).astype(np.int32)
        is_cat = rng.rand(F) > 0.7
        args = (jnp.asarray(leaves), jnp.asarray(rights),
                jnp.asarray(feats), jnp.asarray(thrs),
                jnp.asarray(dlefts), jnp.asarray(cmasks),
                jnp.asarray(valid), jnp.asarray(num_bins),
                jnp.asarray(missing), jnp.asarray(is_cat), L)
        dense = part_ops.apply_wave_splits(
            jnp.asarray(row_leaf), jnp.asarray(bins), *args)
        packed = part_ops.apply_wave_splits(
            jnp.asarray(row_leaf), pb, *args)
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(packed))
        s_dense = part_ops.apply_split(
            jnp.asarray(row_leaf), jnp.asarray(bins), jnp.int32(leaves[0]),
            jnp.int32(rights[0]), jnp.int32(feats[0]), jnp.int32(thrs[0]),
            jnp.bool_(dlefts[0]), jnp.asarray(cmasks[0]),
            jnp.asarray(num_bins), jnp.asarray(missing),
            jnp.asarray(is_cat), jnp.bool_(True))
        s_packed = part_ops.apply_split(
            jnp.asarray(row_leaf), pb, jnp.int32(leaves[0]),
            jnp.int32(rights[0]), jnp.int32(feats[0]), jnp.int32(thrs[0]),
            jnp.bool_(dlefts[0]), jnp.asarray(cmasks[0]),
            jnp.asarray(num_bins), jnp.asarray(missing),
            jnp.asarray(is_cat), jnp.bool_(True))
        np.testing.assert_array_equal(np.asarray(s_dense),
                                      np.asarray(s_packed))


# ---------------------------------------------------------------------------
# end-to-end training parity
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_packed_training_bit_identical():
    """tpu_bin_pack=auto (packed) vs off (uint8 oracle): the full waved
    training loop must produce bit-identical models — the packed layout
    is a re-encoding, not an approximation."""
    X, y = _binary()
    m_off = strip_params(_train(X, y, tpu_bin_pack="off",
                                tpu_fused_grad="off").model_to_string())
    m_on = strip_params(_train(X, y,
                               tpu_fused_grad="off").model_to_string())
    assert m_on == m_off


@pytest.mark.slow
def test_packed_training_bit_identical_quantized():
    """The acceptance fixture: quantized gradients + packed bins vs the
    unpacked oracle — bit-identical (int32 histogram sums are exact)."""
    X, y = _binary()
    m_off = strip_params(_train(X, y, use_quantized_grad=True,
                                tpu_bin_pack="off").model_to_string())
    m_on = strip_params(_train(X, y,
                               use_quantized_grad=True).model_to_string())
    assert m_on == m_off


@pytest.mark.slow
def test_packed_2bit_training():
    """max_bin=3 engages the 2-bit pair layout end to end."""
    X, y = _binary(2000)
    bst_off = _train(X, y, max_bin=3, tpu_bin_pack="off")
    bst_on = _train(X, y, max_bin=3)
    assert lgb.Booster(model_str=bst_on.model_to_string())  # round-trips
    np.testing.assert_array_equal(bst_on.predict(X), bst_off.predict(X))


def test_packed_disabled_when_ineligible():
    X, y = _binary(1500)
    # too many bins
    bst = lgb.Booster({**BASE, "max_bin": 63}, lgb.Dataset(X, label=y))
    assert bst._gbdt._bin_pack_vpb == 1
    # knob off
    bst2 = lgb.Booster({**BASE, "tpu_bin_pack": "off"},
                       lgb.Dataset(X, label=y))
    assert bst2._gbdt._bin_pack_vpb == 1
    # eligible default
    bst3 = lgb.Booster(BASE, lgb.Dataset(X, label=y))
    assert bst3._gbdt._bin_pack_vpb == 2


def test_packed_with_valid_sets_and_exact_grower():
    """Valid-set replay and the exact (tpu_wave_max=0) grower both
    traverse PackedBins; parity vs the unpacked oracle."""
    X, y = _binary(2500)
    Xv, yv = _binary(800, seed=9)
    evals = {}
    preds = {}
    for pack in ("off", "auto"):
        ev = {}
        bst = lgb.train({**BASE, "tpu_bin_pack": pack, "tpu_wave_max": 0,
                         "metric": "auc"},
                        lgb.Dataset(X, label=y), num_boost_round=5,
                        valid_sets=[lgb.Dataset(Xv, label=yv)],
                        valid_names=["v"], callbacks=[
                            lgb.record_evaluation(ev)])
        evals[pack] = ev["v"]["auc"]
        preds[pack] = bst.predict(Xv)
    assert evals["auto"] == evals["off"]
    np.testing.assert_array_equal(preds["auto"], preds["off"])


# ---------------------------------------------------------------------------
# fused gradient/histogram wave
# ---------------------------------------------------------------------------
def test_fused_grad_bit_identical_binary():
    X, y = _binary()
    m_off = strip_params(_train(X, y, tpu_fused_grad="off",
                                tpu_bin_pack="off").model_to_string())
    m_on = strip_params(_train(X, y, tpu_bin_pack="off").model_to_string())
    assert m_on == m_off


@pytest.mark.slow
def test_fused_grad_bit_identical_weighted_regression():
    r = np.random.RandomState(1)
    n = 2500
    X = r.randn(n, 6)
    y = (X[:, 0] * 2 - X[:, 1] + 0.1 * r.randn(n)).astype(np.float32)
    w = np.abs(r.randn(n)).astype(np.float32) + 0.5
    params = {"objective": "regression", "num_leaves": 31, "max_bin": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    outs = {}
    for mode in ("off", "auto"):
        bst = lgb.train({**params, "tpu_fused_grad": mode},
                        lgb.Dataset(X, label=y, weight=w),
                        num_boost_round=6)
        outs[mode] = strip_params(bst.model_to_string())
    assert outs["auto"] == outs["off"]


@pytest.mark.slow
def test_fused_grad_in_kernel_pallas_bit_identical():
    """The pallas path computes gradients INSIDE the multi kernel
    (interpret mode on CPU): must bit-match the pre-built-ghT pallas
    path — same dots, same order, gh computed in VMEM instead of HBM."""
    X, y = _binary()
    m_off = strip_params(_train(X, y, tpu_hist_impl="pallas",
                                tpu_fused_grad="off").model_to_string())
    m_on = strip_params(_train(X, y,
                               tpu_hist_impl="pallas").model_to_string())
    assert m_on == m_off


def test_fused_grad_wide_bins_stay_off_kernel_path():
    """max_bin > 256 stores uint16 bin ids, which the byte-sectioned
    fused kernel cannot represent: the waved grower must fall back to
    the materialized-ghT pallas path (still bit-identical to
    tpu_fused_grad=off) instead of silently aliasing ids & 255."""
    r = np.random.RandomState(3)
    n = 1200
    X = np.repeat(r.randn(n // 4, 4), 4, axis=0) + 0.01 * r.randn(n, 4)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 300,
              "min_data_in_leaf": 5, "verbosity": -1,
              "tpu_hist_impl": "pallas"}
    outs = {}
    for mode in ("off", "auto"):
        bst = lgb.train({**params, "tpu_fused_grad": mode},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        outs[mode] = strip_params(bst.model_to_string())
    assert outs["auto"] == outs["off"]


def test_fused_grad_resolution_gates():
    """GOSS / quantized / multiclass / unsupported objectives keep the
    materialized-gradient path."""
    X, y = _binary(1200)
    assert lgb.Booster(BASE, lgb.Dataset(X, label=y)) \
        ._gbdt._fused_grad_fn is not None
    assert lgb.Booster({**BASE, "tpu_fused_grad": "off"},
                       lgb.Dataset(X, label=y))._gbdt._fused_grad_fn is None
    assert lgb.Booster({**BASE, "data_sample_strategy": "goss"},
                       lgb.Dataset(X, label=y))._gbdt._fused_grad_fn is None
    assert lgb.Booster({**BASE, "use_quantized_grad": True},
                       lgb.Dataset(X, label=y))._gbdt._fused_grad_fn is None
    assert lgb.Booster({**BASE, "objective": "quantile"},
                       lgb.Dataset(X, label=y))._gbdt._fused_grad_fn is None


def test_pointwise_grad_fn_matches_get_gradients():
    """The pointwise forms must be BITWISE equal to get_gradients."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.dataset import Metadata
    r = np.random.RandomState(2)
    n = 1000
    label = (r.rand(n) > 0.5).astype(np.float32)
    weight = np.abs(r.randn(n)).astype(np.float32)
    score = jnp.asarray(r.randn(n), jnp.float32)
    for name, use_w in (("binary", False), ("binary", True),
                        ("regression", False), ("regression", True)):
        cfg = Config.from_params({"objective": name})
        obj = create_objective(cfg)
        md = Metadata(n)
        md.set_label(label)
        if use_w:
            md.set_weight(weight)
        obj.init(md, n)
        fn = obj.pointwise_grad_fn()
        assert fn is not None
        g_ref, h_ref = obj.get_gradients(score)
        g_fn, h_fn = fn(score, obj.label, obj.weight)
        np.testing.assert_array_equal(np.asarray(g_fn), np.asarray(g_ref))
        np.testing.assert_array_equal(np.asarray(h_fn), np.asarray(h_ref))


# ---------------------------------------------------------------------------
# subtraction-aware wave schedule
# ---------------------------------------------------------------------------
def test_wave_schedule_subtraction_awareness():
    from lightgbm_tpu.learner import _wave_schedule
    sub = _wave_schedule(255, 42, 42, 1)
    nosub = _wave_schedule(255, 42, 42, 2)
    assert sum(sub) == sum(nosub) == 254
    assert max(sub) == 42        # one slot per split
    assert max(nosub) == 21      # two slots per split
    assert len(nosub) > len(sub)  # the oracle pays more full-data passes
    # regression guard on the cost model's headline numbers
    assert len(sub) == 13 and len(nosub) == 17


def test_subtract_oracle_training_parity():
    """tpu_wave_subtract=False (both children built, no subtraction)
    agrees with the subtraction path within documented f32 cancellation
    tolerance, and trains the same tree STRUCTURE on this fixture."""
    X, y = _binary()
    b_sub = _train(X, y)
    b_oracle = _train(X, y, tpu_wave_subtract=False)
    np.testing.assert_allclose(b_oracle.predict(X), b_sub.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_hist_traffic_model_counters():
    """The static traffic model: per-wave counters, and >= 1.8x byte
    reduction on the quantized packed fixture shape (the acceptance
    number for ISSUE 7; packing x2 on bins + int8 gh x4 + the
    subtraction-aware 13-vs-17-pass schedule)."""
    from lightgbm_tpu.learner import hist_traffic_model
    kw = dict(num_data=10_500_000, storage_features=28, max_bins=15,
              num_leaves=255, wave_max=42)
    actual = hist_traffic_model(**kw, pack_vpb=2, gh_read_bytes=3,
                                subtract=True, fused_grad=False)
    oracle = hist_traffic_model(**kw, pack_vpb=1, gh_read_bytes=12,
                                subtract=False, fused_grad=False)
    assert len(actual["wave_rows_scanned"]) == actual["passes"]
    assert actual["rows_scanned_per_iter"] == \
        actual["passes"] * kw["num_data"]
    reduction = oracle["hist_bytes_per_iter"] / actual["hist_bytes_per_iter"]
    assert reduction >= 1.8, f"traffic reduction {reduction:.2f} < 1.8"


def test_traffic_meta_reaches_obs_and_model_consistency():
    from lightgbm_tpu.obs.metrics import global_metrics
    X, y = _binary(1500)
    bst = lgb.Booster(BASE, lgb.Dataset(X, label=y))
    ht = global_metrics.meta.get("hist_traffic")
    assert ht is not None and ht["pack_vpb"] == 2 and ht["fused_grad"]
    assert global_metrics.meta["hist_bytes_per_iter"] == \
        ht["hist_bytes_per_iter"]
    assert global_metrics.meta["hist_bytes_reduction"] > 1.0
    assert bst._gbdt._bin_pack_vpb == 2


# ---------------------------------------------------------------------------
# deterministic histogram accumulation (satellite)
# ---------------------------------------------------------------------------
def test_deterministic_hist_tightens_accumulation():
    """Kahan-compensated fixed-chunk accumulation must stay inside the
    1e-4 parity band vs the f64 ground truth on cancellation-heavy
    gradients (at this N both modes are near noise level — the
    compensation's growth-with-N advantage is asserted structurally by
    the shard-regrouping test below, not by racing two tiny errors)."""
    from lightgbm_tpu.ops.histogram import build_histogram
    r = np.random.RandomState(5)
    n, f, b = 200_000, 3, 15
    bins = jnp.asarray(r.randint(0, b, (f, n)), jnp.uint8)
    # huge magnitude spread -> naive f32 accumulation error is visible
    grad = jnp.asarray((r.randn(n) * 10.0 ** r.randint(-3, 4, n))
                       .astype(np.float32))
    hess = jnp.asarray(np.abs(r.randn(n)).astype(np.float32))
    mask = jnp.ones(n, jnp.float32)
    ref64 = np.zeros((f, b, 3))
    bn = np.asarray(bins)
    g64 = np.asarray(grad, np.float64)
    h64 = np.asarray(hess, np.float64)
    for j in range(f):
        for c, v in enumerate((g64, h64, np.ones(n))):
            ref64[j, :, c] = np.bincount(bn[j], weights=v, minlength=b)
    plain = np.asarray(build_histogram(bins, grad, hess, mask, max_bins=b,
                                       impl="xla", row_chunk=8192),
                       np.float64)
    det = np.asarray(build_histogram(bins, grad, hess, mask, max_bins=b,
                                     impl="xla", deterministic=True),
                     np.float64)
    err_plain = np.max(np.abs(plain - ref64) / np.maximum(np.abs(ref64), 1))
    err_det = np.max(np.abs(det - ref64) / np.maximum(np.abs(ref64), 1))
    assert err_det < 1e-4  # the ROADMAP parity target
    assert err_det < 10 * max(err_plain, 1e-9)  # never much worse


def test_deterministic_hist_shard_regrouping():
    """Per-shard deterministic builds summed together (the psum shape)
    must agree with the whole-data deterministic build within the 1e-4
    parity band — the reorders-safely-under-sharding property."""
    from lightgbm_tpu.ops.pallas_histogram import hist_multi_xla
    r = np.random.RandomState(6)
    n, f, b, slots = 50_000, 4, 15, 8
    bins = r.randint(0, b, (f, n)).astype(np.uint8)
    ghT = np.stack([(r.randn(n) * 10.0 ** r.randint(-2, 3, n)),
                    np.abs(r.randn(n)), np.ones(n)],
                   axis=1).astype(np.float32)
    rl = r.randint(0, slots, n).astype(np.int32)
    ids = jnp.asarray(np.arange(slots, dtype=np.int32))

    def det(bv, gv, rv):
        return hist_multi_xla(jnp.asarray(bv), jnp.asarray(gv),
                              jnp.asarray(rv), ids, max_bins=b,
                              num_slots=slots, deterministic=True)

    whole = np.asarray(det(bins, ghT, rl))
    shards = 8
    step = n // shards
    parts = sum(np.asarray(det(bins[:, s * step:(s + 1) * step],
                               ghT[s * step:(s + 1) * step],
                               rl[s * step:(s + 1) * step]))
                for s in range(shards))
    np.testing.assert_allclose(parts, whole,
                               rtol=5e-4, atol=5e-4)
    denom = np.maximum(np.abs(whole), 1.0)
    assert np.max(np.abs(parts - whole) / denom) < 1e-3


def test_deterministic_hist_trains():
    X, y = _binary(2000)
    bst = _train(X, y, deterministic_hist=True, max_bin=63)
    from lightgbm_tpu.metrics import _auc
    assert _auc(y, bst.predict(X)) > 0.9
    # the knob forces the XLA impl
    bst2 = lgb.Booster({**BASE, "deterministic_hist": True,
                        "tpu_hist_impl": "pallas"}, lgb.Dataset(X, label=y))
    assert bst2._gbdt._hist_impl == "xla"


# ---------------------------------------------------------------------------
# int8 promoted to default-capable (satellite)
# ---------------------------------------------------------------------------
def test_int8_xla_matches_pallas_bitwise():
    from lightgbm_tpu.ops.pallas_histogram import (hist_multi_int8,
                                                   hist_multi_int8_xla,
                                                   hist_pallas_multi_int8)
    b, slots = 15, 42
    bins, g_int, h_int, mask, row_leaf = _quant_fixture(b=b)
    ghT_i8 = jnp.asarray(np.stack([g_int, h_int, mask], axis=1), jnp.int8)
    rl = jnp.asarray(row_leaf)
    ids = jnp.asarray([0, 3, 5, 1] + [-2] * (slots - 4), jnp.int32)
    x = hist_multi_int8_xla(jnp.asarray(bins), ghT_i8, rl, ids,
                            max_bins=b, num_slots=slots)
    p = hist_pallas_multi_int8(jnp.asarray(bins), ghT_i8, rl, ids,
                               max_bins=b, num_slots=slots, interpret=True)
    assert x.dtype == p.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(x), np.asarray(p))
    d = hist_multi_int8(jnp.asarray(bins), ghT_i8, rl, ids, max_bins=b,
                        num_slots=slots, impl="xla")
    np.testing.assert_array_equal(np.asarray(d), np.asarray(x))


@pytest.mark.slow
def test_quantized_waved_runs_int8_on_xla():
    """use_quantized_grad on the default (XLA) backend now runs the
    exact-integer int8 histogram — same int32 sums as the device kernel
    — instead of f32 histograms of dequantized values."""
    X, y = _binary()
    m_xla = strip_params(_train(X, y, use_quantized_grad=True,
                                tpu_bin_pack="off").model_to_string())
    m_pal = strip_params(_train(X, y, use_quantized_grad=True,
                                tpu_bin_pack="off",
                                tpu_hist_impl="pallas").model_to_string())
    assert m_xla == m_pal
