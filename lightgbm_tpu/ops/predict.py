"""Device (XLA) batch prediction — the tree-parallel inference engine.

TPU-native analog of the reference prediction kernels
(ref: src/boosting/gbdt_prediction.cpp:16, CUDATree prediction kernels in
src/io/cuda/cuda_tree.cu). Trees are packed into dense [T, ...] tensors
and traversed tree-parallel: node state is a row-major [B, T] tensor and
EVERY tree advances one level per step for the whole row block (leaves
self-loop), so a handful of fused [B, T] flat gathers per depth step
replace the reference's per-tree kernels — the batched device-side
traversal shape of arXiv:1806.11248 §4. (The naive `vmap`-over-trees
formulation broadcasts the row block per tree and measured SLOWER than
the per-tree scan; the row-major layout with raveled-table gathers is
what wins.)

Multiclass is a [T] -> [T/K, K] reshape of the per-tree leaf values
inside the same program (trees are stored class-interleaved: tree
t = iteration*K + class), not K separately compiled subset programs.
Per-class sums accumulate sequentially over the iteration axis, so the
f32 addition order — and therefore the bits — match the old per-tree
scan exactly.

Serving path (`predict_raw_cached`) is a streaming pipeline:

- **Incremental packing** (`EnsemblePacker`): per-iteration eval during
  training appends only the NEW trees into capacity-doubled host
  tensors instead of repacking all T (O(T) amortized over a run, not
  O(T^2)); capacity padding also keeps the traversal program's [T]
  shape stable so recompiles happen O(log T) times, not per iteration.
- **Shape-bucketed chunking**: an uneven final chunk is padded up to a
  power-of-two row bucket, so prediction over any N compiles a small
  fixed set of programs and an N not divisible by the chunk size never
  triggers a fresh JIT (assertable via obs.metrics recompile counters).
- **Double-buffered feed**: chunk i+1's host->device transfer is
  enqueued before chunk i's result is awaited, and all device->host
  gathers happen after the last dispatch, so transfer overlaps
  traversal.
- **Mesh sharding**: with `num_shards`, the row block is `shard_map`ped
  over the "data" axis of a `parallel.mesh` device mesh — a pod serves
  one batch cooperatively.

Categorical splits carry their category-value bitsets in a packed
[T, W] word tensor (the device mirror of tree.h:375 cat_threshold_ +
cat_boundaries_), checked with a dynamic word gather per row.
"""

from __future__ import annotations

import functools
import time
from typing import List, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..obs.metrics import global_metrics
from ..obs.trace import global_tracer

_DEFAULT_LEFT_MASK = 2

# traversal program recompile tag (tests assert chunk-shape stability
# through global_metrics.recompiles(PREDICT_TRACE_TAG))
PREDICT_TRACE_TAG = "predict/traversal"


class PackedEnsemble(NamedTuple):
    """Dense ensemble tensors. T trees, I = max internal nodes, L = max
    leaves, D = max depth. Child convention: >=0 internal, <0 = ~leaf.
    T may include zero-tree capacity padding (num_internal=0, leaf
    value 0 — contributes nothing); `num_trees` is the real count."""
    split_feature: jax.Array   # [T, I] int32
    threshold: jax.Array       # [T, I] f32 (real-valued)
    decision_type: jax.Array   # [T, I] int32
    left_child: jax.Array      # [T, I] int32
    right_child: jax.Array     # [T, I] int32
    leaf_value: jax.Array      # [T, L] f32
    num_internal: jax.Array    # [T] int32
    cat_start: jax.Array       # [T, I] int32 word offset into cat_words
    cat_nwords: jax.Array      # [T, I] int32 word count (0 = not cat)
    cat_words: jax.Array       # [T, W] uint32 bitset words
    max_depth: int             # static
    num_trees_per_class: int   # static (for multiclass reshape)
    num_trees: int = -1        # static real tree count (-1 = all of T)
    has_categorical: bool = True  # static: False elides the bitset ops


_ARRAY_FIELDS = PackedEnsemble._fields[:10]


def _tree_depth(tr) -> int:
    if tr.num_internal == 0:
        return 1
    depth = np.zeros(tr.num_internal, np.int32)
    out = 1
    for nd in range(tr.num_internal):  # parents precede children
        for child in (tr.left_child[nd], tr.right_child[nd]):
            if child >= 0:
                depth[child] = depth[nd] + 1
                out = max(out, int(depth[child]) + 1)
    return out + 1


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


class EnsemblePacker:
    """Incremental host-side ensemble packer.

    Keeps the packed [T, ...] numpy tensors across calls and appends
    only trees it has not seen, identified by a (tree, mutation-version)
    token per tree — the token holds the Tree object itself, so identity
    can't be spoofed by CPython id() recycling after a tree is freed,
    and Tree bumps its version on apply_shrinkage/add_bias, so DART
    renormalization invalidates exactly the rebuilt prefix.
    Capacities grow by doubling — both the tree axis and the per-tree
    dims — so a training run that predicts every iteration packs O(T)
    trees total instead of O(T^2), and the device tensors keep a stable
    shape between capacity doublings (stable shapes = no per-iteration
    traversal recompiles).

    `trees_packed` counts every tree ever written (including rewrites
    during a capacity regrow); tests assert it stays linear in T.
    """

    def __init__(self) -> None:
        self._tokens: List[tuple] = []
        self._depths: List[int] = []
        self._arrs = None          # dict of host numpy arrays at capacity
        self._cap_t = 0            # tree-axis capacity
        self._dims = (0, 0, 0)     # (max_i, max_l, max_w) capacities
        self.num_tree_per_class = 1
        self.trees_packed = 0      # cumulative (monotonic; test hook)
        self.full_repacks = 0
        self._cached = None        # device PackedEnsemble of _tokens
        # TreeSHAP path-table pack (shap_update); cached independently
        # of the traversal pack but under the same identity tokens
        self._shap_tokens: List[tuple] = []
        self._shap_key = None
        self._shap_pack = None
        self.shap_repacks = 0      # full path-table rebuilds (test hook)

    # -- internals -----------------------------------------------------
    def _alloc(self, cap_t: int, max_i: int, max_l: int, max_w: int):
        self._cap_t = cap_t
        self._dims = (max_i, max_l, max_w)
        self._arrs = dict(
            split_feature=np.zeros((cap_t, max_i), np.int32),
            threshold=np.zeros((cap_t, max_i), np.float64),
            decision_type=np.zeros((cap_t, max_i), np.int32),
            left_child=np.full((cap_t, max_i), -1, np.int32),
            right_child=np.full((cap_t, max_i), -1, np.int32),
            leaf_value=np.zeros((cap_t, max_l), np.float32),
            num_internal=np.zeros(cap_t, np.int32),
            cat_start=np.zeros((cap_t, max_i), np.int32),
            cat_nwords=np.zeros((cap_t, max_i), np.int32),
            cat_words=np.zeros((cap_t, max_w), np.uint32),
        )

    def _clear_slot(self, i: int) -> None:
        a = self._arrs
        for f in ("split_feature", "threshold", "decision_type",
                  "cat_start", "cat_nwords"):
            a[f][i] = 0
        a["left_child"][i] = -1
        a["right_child"][i] = -1
        a["leaf_value"][i] = 0
        a["num_internal"][i] = 0
        a["cat_words"][i] = 0

    def _pack_one(self, i: int, tr) -> None:
        a = self._arrs
        n = tr.num_internal
        a["num_internal"][i] = n
        if n:
            a["split_feature"][i, :n] = tr.split_feature[:n]
            a["decision_type"][i, :n] = tr.decision_type[:n]
            a["left_child"][i, :n] = tr.left_child[:n]
            a["right_child"][i, :n] = tr.right_child[:n]
            a["threshold"][i, :n] = tr.threshold[:n]
            if tr.num_cat:
                w = len(tr.cat_threshold)
                a["cat_words"][i, :w] = np.asarray(tr.cat_threshold,
                                                   np.uint32)
                for nd in range(n):
                    if tr.decision_type[nd] & 1:
                        cat_idx = int(tr.threshold[nd])
                        a["cat_start"][i, nd] = tr.cat_boundaries[cat_idx]
                        a["cat_nwords"][i, nd] = (
                            tr.cat_boundaries[cat_idx + 1]
                            - tr.cat_boundaries[cat_idx])
        a["leaf_value"][i, :tr.num_leaves] = tr.leaf_value[:tr.num_leaves]
        self.trees_packed += 1

    @staticmethod
    def _token(tr) -> tuple:
        # tuple equality on (tr, version): Tree has no __eq__, so the
        # first element compares by IDENTITY, and the strong reference
        # pins the object so its id can't be recycled while tracked
        return (tr, getattr(tr, "pack_version", 0))

    # -- TreeSHAP path decomposition -----------------------------------
    def shap_update(self, trees: List, num_tree_per_iteration: int = 1,
                    num_features: int = 1,
                    chunk_rows: int = 4096) -> "ShapPack":
        """Pack-time TreeSHAP path decomposition (GPUTreeShap-style):
        enumerate every root->leaf path of every tree ONCE on the host,
        merge repeated features along each path into unique elements
        (interval-merged numeric thresholds, AND-merged categorical
        bitsets, product zero-fractions), and ravel the result into
        depth-padded [n_chunks, Pc, D] device tables the ops/shap.py
        kernel consumes. Cached under the same (tree, pack_version)
        identity tokens as the traversal pack, so DART renorm / refit /
        rollback invalidate the path tables exactly like traversal."""
        k = max(int(num_tree_per_iteration), 1)
        f = max(int(num_features), 1)
        tokens = [self._token(tr) for tr in trees]
        key = (k, f, int(chunk_rows))
        if (self._shap_pack is not None and key == self._shap_key
                and tokens == self._shap_tokens):
            return self._shap_pack
        self._shap_pack = None
        pack = _build_shap_pack(trees, k, f, int(chunk_rows))
        self._shap_tokens = tokens
        self._shap_key = key
        self._shap_pack = pack
        self.shap_repacks += 1
        return pack

    @property
    def shap_nbytes(self) -> int:
        """Host-side estimate of the path-table pack bytes (the device
        tables mirror the same shapes; see nbytes for the 2x story)."""
        return 0 if self._shap_pack is None else self._shap_pack.nbytes

    @property
    def nbytes(self) -> int:
        """Host bytes held by the packed tensors. The cached device
        ensemble mirrors the same shapes, so total resident cost is
        ~2x this — serve/registry.py budgets with that factor."""
        if self._arrs is None:
            return 0
        return sum(a.nbytes for a in self._arrs.values())

    # -- public --------------------------------------------------------
    def update(self, trees: List, num_tree_per_iteration: int = 1,
               pad: bool = True) -> PackedEnsemble:
        """Pack `trees` (the FULL list), reusing previously packed
        prefixes. pad=False packs to exact dims with no capacity
        headroom (the one-shot `pack_ensemble` path)."""
        k = max(int(num_tree_per_iteration), 1)
        t = len(trees)
        tokens = [self._token(tr) for tr in trees]
        if (self._cached is not None and k == self.num_tree_per_class
                and tokens == self._tokens):
            # identical tree set at identical versions: serve the cached
            # device ensemble — this token compare (not any caller-side
            # key) is the correctness gate, so rollback+retrain key
            # collisions can never resurrect stale packs
            return self._cached
        self._cached = None
        prefix = min(len(self._tokens), t)
        if (self._arrs is None or k != self.num_tree_per_class
                or tokens[:prefix] != self._tokens[:prefix]):
            prefix = 0
        self.num_tree_per_class = k

        new = trees[prefix:]
        need_i = max([tr.num_internal for tr in new] + [1])
        need_l = max([tr.num_leaves for tr in new] + [1])
        need_w = max([len(tr.cat_threshold) for tr in new] + [1])
        max_i, max_l, max_w = self._dims
        grow = (need_i > max_i or need_l > max_l or need_w > max_w
                or t > self._cap_t)
        if prefix == 0 or grow:
            if pad and self._arrs is not None:
                # an append outgrew capacity: double so appends during
                # training touch O(T) trees total and keep stable [T]
                # shapes between regrows
                cap_t = k * _next_pow2(-(-max(t, 1) // k))
                dims = (_next_pow2(max(need_i, max_i)),
                        _next_pow2(max(need_l, max_l)),
                        _next_pow2(max(need_w, max_w)))
            else:
                # first pack (the one-shot serving case): exact shapes —
                # a static ensemble must not pay capacity headroom
                cap_t = max(t, 1)
                dims = (max([tr.num_internal for tr in trees] + [1]),
                        max([tr.num_leaves for tr in trees] + [1]),
                        max([len(tr.cat_threshold) for tr in trees] + [1]))
            self._alloc(cap_t, *dims)
            if prefix > 0:
                self.full_repacks += 1
            prefix = 0
            new = trees
            self._depths = []
        elif t < len(self._tokens):
            # rollback / shorter subset: retire the stale tail slots
            # (prefix == t here, so `new` is already empty)
            for i in range(t, len(self._tokens)):
                self._clear_slot(i)
            self._depths = self._depths[:t]

        for j, tr in enumerate(new):
            self._pack_one(prefix + j, tr)
            self._depths.append(_tree_depth(tr))
        self._tokens = tokens

        depth = max(self._depths, default=1)
        if pad:
            depth = -(-depth // 4) * 4  # bucket: recompile every 4 levels,
            # not every level (extra steps self-loop at leaves — no-ops)
        has_cat = bool(np.any(self._arrs["cat_nwords"]))
        self._cached = PackedEnsemble(
            **{f: jnp.asarray(self._arrs[f]) if f != "threshold"
               else jnp.asarray(self._arrs[f], jnp.float32)
               for f in _ARRAY_FIELDS},
            max_depth=int(depth), num_trees_per_class=k, num_trees=t,
            has_categorical=has_cat)
        return self._cached


def pack_ensemble(trees: List, num_tree_per_iteration: int = 1
                  ) -> PackedEnsemble:
    """Pack host Tree objects (tree.py) into exact-shape device tensors
    (one-shot; the serving path uses an owner-cached EnsemblePacker)."""
    return EnsemblePacker().update(trees, num_tree_per_iteration, pad=False)


# ----------------------------------------------------------------------
# TreeSHAP path decomposition (pack time, host side)
#
# The ops/shap.py kernel evaluates rows x paths: each root->leaf path
# becomes one row of depth-padded element tables, where an "element" is
# one UNIQUE feature on the path (the reference recursion's dedup/unwind
# merges repeated features on the fly; we merge them once at pack time):
#
# - zero_fraction = product of taken-child cover ratios over the
#   feature's occurrences (exactly the incoming_zero_fraction product
#   the recursion accumulates through _unwind_path);
# - one_fraction is 0/1 (a row either follows the whole path at this
#   feature or not), so the per-row decision merges too: numeric
#   occurrences collapse to an (lo, hi] interval in f32 (matching the
#   device traversal's f32 threshold compare), categorical occurrences
#   AND their direction-oriented bitset images into one merged bitset;
# - missing routing merges as AND over "does the default direction
#   follow this path here" (default_follows / oor_follows).
#
# Every path is padded to a uniform D slots with NEUTRAL elements
# (one_fraction = zero_fraction = 1): extending a path by a (1,1)
# element never changes any real element's unwound weight — the dummy
# root element the reference recursion starts from is exactly such an
# element — so padded paths stay bit-for-bit consistent with the
# variable-depth recursion while giving the kernel static shapes.

_SHAP_TABLE_FIELDS = (
    "feature", "z", "z_inv", "lo", "hi", "no_lo", "default_follows",
    "is_cat", "oor_follows", "mt", "cat_start", "cat_nwords", "segid")

# working-set budget for the [B, Pc, D] kernel temporaries (pweights,
# one-fractions, unwound totals, ...): Pc (the path-chunk width) is
# sized so ~6 such tensors at the row-chunk cap fit in this budget
_SHAP_BUDGET_BYTES = 128 << 20


class ShapPack(NamedTuple):
    """Depth-padded TreeSHAP path tables. P paths pad to n_chunks * Pc
    rows; every path pads to D element slots (slot 0 is the dummy root
    element). Neutral slots carry z = 1 and decide to one_fraction = 1,
    so they contribute (1 - 1) * w = 0; their segid points at the trash
    column num_class * (F + 1), which the kernel slices off."""
    tables: tuple          # 13 [n_chunks, Pc, D] arrays (_SHAP_TABLE_FIELDS)
    leaf_value: jax.Array  # [n_chunks, Pc] f32
    cat_words: jax.Array   # [W] uint32 merged bitset words (>= 1 word)
    bias: np.ndarray       # [K] f64 per-class expected values (host)
    num_paths: int
    depth: int             # D (element slots incl. dummy root)
    path_chunk: int        # Pc
    num_chunks: int
    num_features: int
    num_class: int
    has_categorical: bool  # static: False elides the bitset ops
    nbytes: int


def _shap_child_count(tr, child: int) -> float:
    return float(tr.leaf_count[~child]) if child < 0 else \
        float(tr.internal_count[child])


def _shap_paths_of_tree(tr):
    """[(occurrences, leaf_value)] per root->leaf path, where an
    occurrence is (node, went_left) in root->leaf order. Iterative so
    deep trees can't blow the recursion limit."""
    out = []
    if tr.num_internal == 0:
        return out
    stack = [(0, [])]
    while stack:
        node, occs = stack.pop()
        for went_left in (True, False):
            child = int(tr.left_child[node] if went_left
                        else tr.right_child[node])
            occ2 = occs + [(node, went_left)]
            if child < 0:
                out.append((occ2, float(tr.leaf_value[~child])))
            else:
                stack.append((child, occ2))
    return out


def _shap_merge_elements(tr, occs):
    """Merge one path's occurrences into unique per-feature elements
    (first-occurrence order; order is irrelevant to the math)."""
    elements = {}
    order = []
    for node, went_left in occs:
        taken = int(tr.left_child[node] if went_left
                    else tr.right_child[node])
        count = int(tr.internal_count[node])
        denom = float(count) if count > 0 else 1.0
        feat = int(tr.split_feature[node])
        dt = int(tr.decision_type[node])
        el = elements.get(feat)
        if el is None:
            el = elements[feat] = dict(
                feature=feat, z=1.0, lo=-np.inf, no_lo=True, hi=np.inf,
                default_follows=True, is_cat=bool(dt & 1),
                mt=(dt >> 2) & 3, oor_follows=True, cat_occ=[])
            order.append(feat)
        el["z"] *= _shap_child_count(tr, taken) / denom
        default_left = bool(dt & _DEFAULT_LEFT_MASK)
        el["default_follows"] &= (default_left == went_left)
        if el["is_cat"]:
            cat_idx = int(tr.threshold[node])
            w_lo = tr.cat_boundaries[cat_idx]
            w_hi = tr.cat_boundaries[cat_idx + 1]
            words = np.asarray(tr.cat_threshold[w_lo:w_hi], np.uint32)
            el["cat_occ"].append((words, went_left))
            # values outside every occurrence's bitset range go right
            el["oor_follows"] &= (not went_left)
        else:
            # f32 threshold compare, matching the device traversal pack
            thr = float(np.float32(tr.threshold[node]))
            if went_left:
                el["hi"] = min(el["hi"], thr)
            else:
                el["lo"] = max(el["lo"], thr)
                el["no_lo"] = False
    return [elements[feat] for feat in order]


def _shap_merge_cat_words(el) -> np.ndarray:
    """AND the direction-oriented images of each occurrence's bitset:
    a category follows the path iff it takes the recorded direction at
    EVERY occurrence. Left-taken occurrences contribute their words
    (in-set bit = goes left = follows), right-taken contribute the
    complement; words beyond an occurrence's own range image to 0 (left
    expects in-set, out-of-range is not) or all-ones (right)."""
    width = max(len(words) for words, _ in el["cat_occ"])
    merged = np.full(width, 0xFFFFFFFF, np.uint32)
    for words, went_left in el["cat_occ"]:
        if went_left:
            img = np.zeros(width, np.uint32)
            img[:len(words)] = words
        else:
            img = np.full(width, 0xFFFFFFFF, np.uint32)
            img[:len(words)] = ~words
        merged &= img
    return merged


def _pow2_floor(n: int) -> int:
    return 1 << (max(int(n), 1).bit_length() - 1)


def _shap_path_chunk(num_paths: int, depth: int, chunk_rows: int) -> int:
    """Pc: paths per kernel invocation, sized so the [B, Pc, D] f32
    working set (~6 tensors) at the row-chunk cap stays inside the
    budget. Power of two so path counts bucket like row counts do."""
    per_path = max(int(chunk_rows) * int(depth) * 4 * 6, 1)
    pc = max(_pow2_floor(_SHAP_BUDGET_BYTES // per_path), 32)
    return min(pc, _next_pow2(max(num_paths, 1)))


def _build_shap_pack(trees: List, k: int, num_features: int,
                     chunk_rows: int) -> ShapPack:
    from ..shap import _expected_value
    f = num_features
    num_out = k * (f + 1)
    bias = np.zeros(k, np.float64)
    paths = []  # (class, elements, leaf_value)
    for j, tr in enumerate(trees):
        ki = j % k
        bias[ki] += _expected_value(tr)
        for occs, leaf_value in _shap_paths_of_tree(tr):
            paths.append((ki, _shap_merge_elements(tr, occs), leaf_value))

    num_paths = len(paths)
    # D: dummy root slot + max unique elements, bucketed to a multiple
    # of 4 (same recompile-bucketing story as the traversal depth)
    depth = 1 + max((len(els) for _, els, _ in paths), default=0)
    depth = max(-(-depth // 4) * 4, 4)
    pc = _shap_path_chunk(num_paths, depth, chunk_rows)
    p_pad = -(-max(num_paths, 1) // pc) * pc
    n_chunks = p_pad // pc

    arrs = dict(
        feature=np.full((p_pad, depth), -1, np.int32),
        z=np.ones((p_pad, depth), np.float32),
        z_inv=np.ones((p_pad, depth), np.float32),
        lo=np.zeros((p_pad, depth), np.float32),
        hi=np.full((p_pad, depth), np.inf, np.float32),
        no_lo=np.ones((p_pad, depth), np.bool_),
        default_follows=np.zeros((p_pad, depth), np.bool_),
        is_cat=np.zeros((p_pad, depth), np.bool_),
        oor_follows=np.zeros((p_pad, depth), np.bool_),
        mt=np.zeros((p_pad, depth), np.int32),
        cat_start=np.zeros((p_pad, depth), np.int32),
        cat_nwords=np.zeros((p_pad, depth), np.int32),
        segid=np.full((p_pad, depth), num_out, np.int32),
    )
    leaf_value = np.zeros(p_pad, np.float32)
    cat_words: List[np.ndarray] = []
    cat_offset = 0
    for p, (ki, els, lv) in enumerate(paths):
        leaf_value[p] = lv
        for d, el in enumerate(els, start=1):  # slot 0 = dummy root
            z = el["z"]
            arrs["feature"][p, d] = el["feature"]
            arrs["z"][p, d] = z
            arrs["z_inv"][p, d] = 1.0 / z if z > 0 else 0.0
            arrs["segid"][p, d] = ki * (f + 1) + el["feature"]
            arrs["mt"][p, d] = el["mt"]
            arrs["default_follows"][p, d] = el["default_follows"]
            if el["is_cat"]:
                words = _shap_merge_cat_words(el)
                arrs["is_cat"][p, d] = True
                arrs["oor_follows"][p, d] = el["oor_follows"]
                arrs["cat_start"][p, d] = cat_offset
                arrs["cat_nwords"][p, d] = len(words)
                cat_words.append(words)
                cat_offset += len(words)
            else:
                arrs["lo"][p, d] = el["lo"] if not el["no_lo"] else 0.0
                arrs["no_lo"][p, d] = el["no_lo"]
                arrs["hi"][p, d] = el["hi"]

    words_flat = (np.concatenate(cat_words) if cat_words
                  else np.zeros(1, np.uint32))
    nbytes = (sum(a.nbytes for a in arrs.values()) + leaf_value.nbytes
              + words_flat.nbytes)
    tables = tuple(
        jnp.asarray(arrs[name].reshape(n_chunks, pc, depth))
        for name in _SHAP_TABLE_FIELDS)
    return ShapPack(
        tables=tables,
        leaf_value=jnp.asarray(leaf_value.reshape(n_chunks, pc)),
        cat_words=jnp.asarray(words_flat), bias=bias,
        num_paths=num_paths, depth=depth, path_chunk=pc,
        num_chunks=n_chunks, num_features=f, num_class=k,
        has_categorical=bool(cat_words), nbytes=int(nbytes))


def _predict_leaf_one_tree(tree, x, max_depth: int):
    """Leaf index per row for one packed tree (tuple of arrays).
    Traceable; `vmap` over the tree axis advances all trees at once."""
    sf, th, dt, lc, rc, ni, cs, cn, cw = tree
    num_rows = x.shape[0]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        feat = sf[nd]
        val = jnp.take_along_axis(x, feat[:, None], axis=1)[:, 0]
        thr = th[nd]
        d = dt[nd]
        default_left = (d & _DEFAULT_LEFT_MASK) > 0
        missing_type = (d >> 2) & 3
        is_cat = (d & 1) > 0
        isnan = jnp.isnan(val)
        v0 = jnp.where(isnan, 0.0, val)
        # categorical bitset decision (ref: tree.h:375 CategoricalDecision)
        v_int = v0.astype(jnp.int32)
        widx = jnp.clip(cs[nd] + v_int // 32, 0, cw.shape[0] - 1)
        word = cw[widx]
        in_range = (~isnan) & (v0 >= 0) & (v_int // 32 < cn[nd])
        cat_left = in_range & (
            (word >> (v_int % 32).astype(jnp.uint32)) & 1 > 0)
        go_left = jnp.where(is_cat, cat_left, v0 <= thr)
        use_default = (isnan & (missing_type == 2)) | \
            ((missing_type == 1) & (isnan | (jnp.abs(v0) <= 1e-35)))
        go_left = jnp.where(use_default & ~is_cat, default_left, go_left)
        nxt = jnp.where(go_left, lc[nd], rc[nd])
        # leaves (node < 0) self-loop
        return jnp.where(node < 0, node, nxt)

    node0 = jnp.where(ni > 0, jnp.zeros(num_rows, jnp.int32),
                      jnp.full(num_rows, -1, jnp.int32))
    node = lax.fori_loop(0, max_depth, body, node0)
    return jnp.where(node < 0, ~node, 0)


def _tree_operands(ens: PackedEnsemble):
    return (ens.split_feature, ens.threshold, ens.decision_type,
            ens.left_child, ens.right_child, ens.num_internal,
            ens.cat_start, ens.cat_nwords, ens.cat_words)


def predict_leaves_all(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] -> [B, T] leaf index per (row, tree): the tree-parallel
    traversal. Node state is [B, T] row-major — every tree advances one
    level per step for the whole row block — and all table lookups are
    flat gathers into the raveled [T*I] node tables, so the per-step
    working set per row is one x row plus the (cache-resident) tree
    tables. Measured on the serving bench shape (CPU, T=100, 255
    leaves): ~4x the per-tree `lax.scan` path this replaced; the
    vmapped [T, B] formulation broadcast the row block per tree and
    came out slower than the scan. Flat indices are int32: callers
    must keep B*F (and T*I) below 2^31 — predict_raw_cached clamps its
    chunk size to guarantee this."""
    sf, th, dt, lc, rc, ni, cs, cn, cw = _tree_operands(ens)
    t, i = sf.shape
    b, f = x.shape
    w = cw.shape[1]
    sf_f, th_f, dt_f, lc_f, rc_f, cs_f, cn_f = (
        jnp.ravel(a) for a in (sf, th, dt, lc, rc, cs, cn))
    cw_f = jnp.ravel(cw)
    toff = (jnp.arange(t, dtype=jnp.int32) * i)[None, :]   # [1, T]
    woff = (jnp.arange(t, dtype=jnp.int32) * w)[None, :]
    x_f = jnp.ravel(x)
    brow = (jnp.arange(b, dtype=jnp.int32) * f)[:, None]   # [B, 1]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        fi = nd + toff                       # flat [B, T] node-table index
        val = x_f[sf_f[fi] + brow]
        d = dt_f[fi]
        default_left = (d & _DEFAULT_LEFT_MASK) > 0
        missing_type = (d >> 2) & 3
        isnan = jnp.isnan(val)
        v0 = jnp.where(isnan, 0.0, val)
        numeric_left = v0 <= th_f[fi]
        if ens.has_categorical:
            # categorical bitset decision (ref: tree.h:375
            # CategoricalDecision); statically elided for ensembles
            # without categorical splits — the common serving case
            is_cat = (d & 1) > 0
            v_int = v0.astype(jnp.int32)
            widx = jnp.clip(cs_f[fi] + v_int // 32, 0, w - 1)
            word = cw_f[widx + woff]
            in_range = (~isnan) & (v0 >= 0) & (v_int // 32 < cn_f[fi])
            cat_left = in_range & (
                (word >> (v_int % 32).astype(jnp.uint32)) & 1 > 0)
            go_left = jnp.where(is_cat, cat_left, numeric_left)
            not_cat = ~is_cat
        else:
            go_left = numeric_left
            not_cat = True
        use_default = (isnan & (missing_type == 2)) | \
            ((missing_type == 1) & (isnan | (jnp.abs(v0) <= 1e-35)))
        go_left = jnp.where(use_default & not_cat, default_left, go_left)
        nxt = jnp.where(go_left, lc_f[fi], rc_f[fi])
        # leaves (node < 0) self-loop
        return jnp.where(node < 0, node, nxt)

    node0 = jnp.where((ni > 0)[None, :], jnp.zeros((b, t), jnp.int32), -1)
    node = lax.fori_loop(0, ens.max_depth, body, node0)
    return jnp.where(node < 0, ~node, 0)


def _class_sums(ens: PackedEnsemble, leaves: jax.Array) -> jax.Array:
    """[B, T] leaves -> [B, K] raw scores. Trees are class-interleaved
    (tree t = iteration*K + class), so a [T] -> [T/K, K] reshape of the
    per-tree leaf values replaces the old K-subset-programs loop; the
    per-class accumulation runs sequentially over the iteration axis so
    f32 addition order (and bits) match the old per-tree scan."""
    k = max(ens.num_trees_per_class, 1)
    t = leaves.shape[1]
    lv = ens.leaf_value
    lv_f = jnp.ravel(lv)
    loff = (jnp.arange(t, dtype=jnp.int32) * lv.shape[1])[None, :]
    vals = lv_f[leaves + loff]                  # [B, T]
    vals = vals.reshape(-1, t // k, k)

    def body(i, acc):
        return acc + vals[:, i, :]

    return lax.fori_loop(0, t // k, body,
                         jnp.zeros((vals.shape[0], k), jnp.float32))


def predict_raw(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] raw features (NaN = missing) -> raw scores [B] (all
    trees summed into one stream). Traceable inside an outer jit."""
    one = ens._replace(num_trees_per_class=1)
    return _class_sums(one, predict_leaves_all(ens, x))[:, 0]


def predict_raw_multiclass(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """-> [B, K] for K = num_trees_per_class class streams, in ONE
    program (no per-class subset ensembles, host- or device-side)."""
    return _class_sums(ens, predict_leaves_all(ens, x))


def predict_leaf_index(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """x: [B, F] -> leaf indices [B, T] (ref: PredictLeafIndex)."""
    leaves = predict_leaves_all(ens, x)
    t = ens.num_trees
    return leaves if t < 0 else leaves[:, :t]


def predict_raw_scan(ens: PackedEnsemble, x: jax.Array) -> jax.Array:
    """The pre-engine per-tree `lax.scan` traversal, kept as the bench
    baseline and parity oracle for the tree-parallel path: same math,
    trees advance one AT A TIME. -> [B, K]."""
    num_rows = x.shape[0]
    k = max(ens.num_trees_per_class, 1)

    def one_class(ki):
        idx = jnp.arange(ki, ens.split_feature.shape[0], k)
        ops = tuple(jnp.take(a, idx, axis=0) for a in _tree_operands(ens))
        lv = jnp.take(ens.leaf_value, idx, axis=0)

        def one_tree(carry, tree):
            *nav, tlv = tree
            leaf = _predict_leaf_one_tree(tuple(nav), x, ens.max_depth)
            return carry + tlv[leaf], None

        total, _ = lax.scan(one_tree, jnp.zeros(num_rows, jnp.float32),
                            ops + (lv,))
        return total

    return jnp.stack([one_class(ki) for ki in range(k)], axis=1)


# ----------------------------------------------------------------------
# streaming serving pipeline
def _resolve_mesh(num_shards: int):
    if not num_shards or num_shards == 1:
        return None
    if len(jax.devices()) <= 1:
        # single device: sharding degrades to serial — expected, silent
        return None
    try:
        from ..parallel.mesh import get_mesh
        mesh = get_mesh(num_shards)
        return mesh if mesh.size > 1 else None
    except Exception as exc:
        # an explicit tpu_num_shards>1 request must not misroute quietly
        from .. import log
        log.warning(f"sharded predict unavailable "
                    f"(num_shards={num_shards}): {exc!r}; "
                    "falling back to single-device traversal")
        return None


@functools.lru_cache(maxsize=64)
def _traversal_program(mesh, k: int, max_depth: int, has_cat: bool = True):
    """jit(program) over (10 packed arrays, x) -> [B, K]; optionally
    shard_mapped over the data axis of `mesh`. Cached per (mesh, K,
    depth, cat) — array shapes key the underlying jit cache, and the
    wrap_traced tag feeds obs.metrics recompile counters."""
    def run(*args):
        ens = PackedEnsemble(*args[:-1], max_depth=max_depth,
                             num_trees_per_class=k,
                             has_categorical=has_cat)
        return predict_raw_multiclass(ens, args[-1])

    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from ..parallel import mesh as mesh_lib
        rep = P()
        run = mesh_lib.shard_map(
            run, mesh=mesh,
            in_specs=tuple([rep] * len(_ARRAY_FIELDS))
            + (P(mesh_lib.DATA_AXIS, None),),
            out_specs=P(mesh_lib.DATA_AXIS, None))
    from ..obs import xla as obs_xla
    return obs_xla.instrumented_jit(PREDICT_TRACE_TAG, run, phase="predict")


def _row_bucket(rows: int, chunk: int, mesh) -> int:
    """Pad target for a chunk of `rows`: full chunks stay `chunk`; an
    uneven tail rounds up to a power of two while small (so tiny
    predicts waste at most 2x compute) and to a chunk/16 multiple once
    large (so big tails waste at most ~6%). Either way any N compiles
    only a small bounded set of row shapes — never a per-N program."""
    if rows >= chunk:
        b = chunk
    else:
        grain = max(chunk // 16, 16)
        b = (_next_pow2(max(rows, 16)) if rows < grain
             else min(-(-rows // grain) * grain, chunk))
    if mesh is not None:
        from ..parallel.mesh import pad_rows_to_shards
        b = pad_rows_to_shards(b, mesh)
    return b


def _get_packer(owner, cache_key):
    """Owner-cached EnsemblePacker, keyed by the prediction window start
    so alternating sub-range predicts don't thrash one packer's prefix.
    `owner._packed_key = None` (capi's post-surgery invalidation) drops
    every packer: in-place tree edits don't change identity tokens."""
    if getattr(owner, "_packed_key", "unset") is None:
        owner._packers = {}
    packers = getattr(owner, "_packers", None)
    if packers is None:
        packers = owner._packers = {}
    pk = cache_key[0] if isinstance(cache_key, tuple) and cache_key else None
    packer = packers.get(pk)
    if packer is None:
        while len(packers) >= 8:  # bound host memory across odd sub-ranges
            packers.pop(next(iter(packers)))
        packer = packers[pk] = EnsemblePacker()
    return packer


def predict_raw_cached(owner, trees: List, num_tree_per_iteration: int,
                       data: np.ndarray, cache_key,
                       chunk: int = 1 << 20,
                       num_shards: int = 0) -> np.ndarray:
    """Raw [N, K] prediction through the packed device ensemble — the
    streaming inference engine. Packed tensors are cached on `owner`
    (incrementally appended, see EnsemblePacker) under `cache_key`.
    GBDT and LoadedModel (model_io.py) both predict through this
    helper, so a save/load round trip runs the identical XLA program
    and returns bit-equal outputs (the reference gets the same property
    by sharing GBDT::PredictRaw between live and loaded boosters,
    gbdt_prediction.cpp:16)."""
    k = max(int(num_tree_per_iteration), 1)
    # ALWAYS revalidate through the packer's identity tokens: the
    # caller's cache_key only selects a packer (and carries capi's
    # None-invalidation); correctness never rides on key uniqueness
    # (a rollback + retrain can reproduce an old (start, end, iter) key
    # with different trees — the token compare catches that, and it is
    # O(T) cheap when nothing changed)
    packer = _get_packer(owner, cache_key)
    with global_tracer.span("predict/pack"):
        ens = owner._packed = packer.update(trees, num_tree_per_iteration)
    owner._packed_key = cache_key
    n = data.shape[0]
    if n == 0:
        return np.zeros((0, k))
    mesh = _resolve_mesh(num_shards)
    ms = mesh.size if mesh is not None else 1
    # flat row*F+feature gathers index in int32: keep every chunk's
    # B*F below 2^31 (wide-feature data just streams smaller chunks).
    # The cap is floored to a mesh multiple so _row_bucket's round-UP
    # to the shard count can never push a bucket back over the bound.
    cap = ((1 << 31) - 1) // max(int(data.shape[1]), 1)
    cap = max(cap // ms * ms, ms)
    chunk = max(1, min(int(chunk), cap))
    prog = _traversal_program(mesh, k, ens.max_depth, ens.has_categorical)
    arrs = tuple(getattr(ens, f) for f in _ARRAY_FIELDS)
    sharding = None
    if mesh is not None:
        from ..parallel.mesh import data_sharding
        sharding = data_sharding(mesh, ndim=2)

    bounds = [(lo, min(lo + chunk, n)) for lo in range(0, n, chunk)]

    def stage(lo, hi):
        """Enqueue one (padded) chunk's host->device transfer."""
        rows = hi - lo
        b = _row_bucket(rows, chunk, mesh)
        xb = np.zeros((b, data.shape[1]), np.float32)
        xb[:rows] = data[lo:hi]
        dev = (jax.device_put(xb, sharding) if sharding is not None
               else jax.device_put(xb))
        return dev, rows

    t0 = time.perf_counter()
    with global_tracer.span("predict/traversal"):
        parts = []
        # double-buffer: chunk i+1's transfer overlaps chunk i's
        # traversal (device_put and the jitted call are both async) —
        # the shared pipeline implementation in io/streaming.py, also
        # used by out-of-core training's slab feed
        from ..io.streaming import double_buffered
        for dev, rows in double_buffered(bounds,
                                         lambda b: stage(*b)):
            parts.append((prog(*arrs, dev), rows))
        out = np.concatenate(
            [np.asarray(y, np.float64)[:rows] for y, rows in parts], axis=0)
    global_metrics.note_predict(n, time.perf_counter() - t0)
    return out
