"""Serialized AOT serving artifacts — warm a replica from disk.

``LowLatencyPredictor``'s warm state is one compiled XLA executable per
(row-bucket, feature-width). Before this module that state existed only
in process memory: every replica restart, and every LRU pack eviction's
later re-admission, re-ran ``jit().lower().compile()`` for the whole
bucket ladder. This module persists those executables through
``jax.experimental.serialize_executable`` so a restarted ``ModelServer``
(or a re-admitted model) warms from disk in milliseconds with ZERO
``serve/lowlat`` compiles — asserted via obs counters by
``tools/check_coldstart.py`` and perf-gate check 10.

Keying / invalidation: every artifact carries a fingerprint —

- ``artifact_version`` (this module's on-disk format),
- ``jax`` / ``jaxlib`` versions and the backend platform + device kind
  and count (a serialized executable is machine code for ONE runtime),
- the packed-ensemble layout (``PackedEnsemble`` field names + per-
  field shapes/dtypes — the "pack version" of the serving tensors) and
  a content digest of the host-side trees (so a retrained/mutated
  model can never load a stale executable; see ``trees_digest``),
- the (row-bucket, feature-width) program identity.

``load`` returns None on ANY mismatch, missing file, or deserialize
failure; the caller then compiles exactly as before — artifacts are an
accelerator, never a correctness dependency, and predictions are
bit-identical either way (the deserialized executable IS the compiled
program that was serialized).

Counters (always-on ``obs.metrics``, exported as ``lgbmtpu_serve_*``):

- ``serve/aot_loads``           — executables restored from disk
- ``serve/aot_exports``         — executables serialized to disk
- ``serve/aot_load_failures``   — fingerprint mismatch / corrupt /
  failed deserialize (each one fell back to a real compile)
- ``serve/aot_export_failures`` — serialize or save-time validation
  failed (nothing was published; see ``ArtifactStore.save``)
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

import numpy as np

from ..obs.metrics import global_metrics

# on-disk format version: bump when the payload layout below changes
ARTIFACT_VERSION = 1


def serialize_available() -> bool:
    """Whether this jax exposes executable serialization at all —
    callers skip the store gracefully when it doesn't."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return True
    except Exception:
        return False


def backend_fingerprint() -> Dict[str, Any]:
    """The runtime identity a serialized executable is only valid for."""
    import jax
    try:
        import jaxlib
        jaxlib_v = str(jaxlib.__version__)
    except Exception:
        jaxlib_v = "?"
    try:
        dev = jax.devices()[0]
        kind = str(getattr(dev, "device_kind", "?"))
        n_dev = int(jax.device_count())
    except Exception:
        kind, n_dev = "?", 0
    return {
        "artifact_version": ARTIFACT_VERSION,
        "jax": str(jax.__version__),
        "jaxlib": jaxlib_v,
        "platform": str(jax.default_backend()),
        "device_kind": kind,
        "n_devices": n_dev,
    }


def trees_digest(trees, num_tree_per_iteration: int = 1) -> str:
    """Content digest of the HOST-side trees — the model-identity half
    of the artifact key. Any retrain or mutation (apply_shrinkage,
    add_bias, refit) changes the hashed arrays, so a stale executable
    can never be loaded for a changed model. Hashing the trees instead
    of the packed device tensors keeps key construction free of
    device->host readbacks (the packed tensors' shapes/dtypes are keyed
    separately by the caller — they are host-known without transfer)."""
    h = hashlib.sha256()
    h.update(str(int(num_tree_per_iteration)).encode())
    h.update(str(len(trees)).encode())
    for tr in trees:
        n = int(tr.num_internal)
        h.update(str(n).encode())
        for arr in (tr.split_feature[:n], tr.threshold[:n],
                    tr.decision_type[:n], tr.left_child[:n],
                    tr.right_child[:n], tr.leaf_value):
            host = np.ascontiguousarray(arr)
            h.update(str(host.dtype).encode())
            h.update(host.tobytes())
        if getattr(tr, "num_cat", 0):
            h.update(np.ascontiguousarray(
                tr.cat_threshold, np.uint32).tobytes())
    return h.hexdigest()[:24]


class ArtifactStore:
    """Directory-backed store of serialized AOT executables.

    One file per executable, named by the SHA-256 of the canonical
    fingerprint JSON — models can share a directory without collisions,
    and a changed fingerprint is simply a different filename (the stale
    file ages out; it is never wrongly loaded). Writes are atomic
    (tempfile + rename) so a crashed export can't strand a torn
    artifact for a later replica to trip over.
    """

    def __init__(self, root: str):
        self.root = str(root)

    def _path(self, key: Dict[str, Any]) -> str:
        canon = json.dumps(key, sort_keys=True, separators=(",", ":"))
        name = hashlib.sha256(canon.encode()).hexdigest()[:32]
        return os.path.join(self.root, f"{name}.aotx")

    def has(self, key: Dict[str, Any]) -> bool:
        """Whether an artifact is stored under `key` (no load attempt)."""
        return os.path.exists(self._path(key))

    # ------------------------------------------------------------------
    def save(self, key: Dict[str, Any], compiled) -> bool:
        """Serialize `compiled` under `key`. Best-effort: False on any
        failure (backends without serialization, read-only disk).

        The payload is VALIDATED by deserializing it back before it is
        written: some backend/executable combinations serialize without
        error but produce a blob that cannot load (e.g. an executable
        that itself came out of the XLA disk cache re-serializes with
        dangling fusion symbols on jaxlib<=0.4.36). A store must never
        publish an artifact a restarted replica would trip over —
        counted under ``serve/aot_export_failures``."""
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            se.deserialize_and_load(payload, in_tree, out_tree)
            blob = pickle.dumps({"key": key, "payload": payload,
                                 "in_tree": in_tree, "out_tree": out_tree},
                                protocol=pickle.HIGHEST_PROTOCOL)
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, self._path(key))
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception:
            global_metrics.inc_counter("serve/aot_export_failures")
            return False
        global_metrics.inc_counter("serve/aot_exports")
        return True

    def load(self, key: Dict[str, Any]):
        """Deserialize the executable stored under `key`, or None on any
        miss/mismatch/corruption (the caller recompiles). A plain miss
        is silent; an EXISTING file that fails to load counts a
        ``serve/aot_load_failures``."""
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            from jax.experimental import serialize_executable as se
            with open(path, "rb") as fh:
                rec = pickle.load(fh)
            # defense in depth: the filename hash already encodes the
            # fingerprint, but verify the stored key verbatim so a hash
            # collision or a hand-renamed file can never smuggle a
            # foreign executable into this model
            if rec.get("key") != key:
                raise ValueError("artifact fingerprint mismatch")
            compiled = se.deserialize_and_load(
                rec["payload"], rec["in_tree"], rec["out_tree"])
        except Exception:
            global_metrics.inc_counter("serve/aot_load_failures")
            return None
        global_metrics.inc_counter("serve/aot_loads")
        return compiled

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n.endswith(".aotx"))
        except OSError:
            return 0


def open_store(artifact_dir: Optional[str]) -> Optional[ArtifactStore]:
    """An ArtifactStore for `artifact_dir`, or None when the dir is
    unset/empty or this jax cannot serialize executables at all."""
    if not artifact_dir:
        return None
    if not serialize_available():
        return None
    return ArtifactStore(artifact_dir)
