#!/usr/bin/env python
"""Chaos validator for the resilience layer (ISSUE 11).

Drives the REAL recovery paths with the deterministic fault plan
(lightgbm_tpu/resilience/faults.py) and fails loudly if any of them
regressed — this is how the checkpoint/resume, corruption-rejection
and graceful-degradation code stays honest instead of untested:

1. **Kill/resume bit-parity** — train N iterations straight, then
   train with an injected preemption at iteration k (the SIGTERM path:
   finish the iteration, snapshot, ``SystemExit(EXIT_PREEMPTED)``),
   re-run the same command to resume, and assert the final
   ``model_to_string()`` is BIT-identical to the uninterrupted run.
2. **Corruption rejection** — flip one payload byte of the checkpoint
   just written (fault plan) and assert the loader refuses with
   ``CorruptCheckpointError``; truncate a model file mid-ensemble and
   assert ``CorruptModelError`` names a byte offset.
3. **Serve degradation observed via /metrics** — against a live
   ``ModelServer`` with its OpenMetrics endpoint: an expired deadline
   fails fast, an overloaded admission queue sheds with retry-after,
   an injected transient fault is retried to a bit-exact answer, and
   repeated faults trip the per-model circuit breaker — each observed
   as a nonzero ``lgbmtpu_resilience_*`` family in a real ``/metrics``
   scrape, plus the breaker-open gauge.

Exit 0 = all steps passed. Wired into the quick verification tier via
tests/test_resilience.py.
"""

import asyncio
import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _fixture(n=260, f=6, seed=3):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * r.randn(n) > 0.4)
    return X, y.astype(np.float32)


def step1_kill_resume(tmpdir) -> None:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience import faults as fm
    from lightgbm_tpu.resilience.errors import EXIT_PREEMPTED

    X, y = _fixture()
    ck = os.path.join(tmpdir, "train.ckpt")
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "bagging_fraction": 0.8, "bagging_freq": 2,
              "tpu_checkpoint_path": ck, "tpu_checkpoint_every": 3}
    straight = lgb.train(dict(params), lgb.Dataset(X, y),
                         num_boost_round=8).model_to_string()
    os.remove(ck)

    fm.install(fm.FaultPlan(kill_at_iter=4))
    try:
        lgb.train(dict(params), lgb.Dataset(X, y), num_boost_round=8)
        raise AssertionError("injected preemption did not exit")
    except SystemExit as e:
        assert e.code == EXIT_PREEMPTED, \
            f"preemption exit code {e.code} != {EXIT_PREEMPTED}"
    finally:
        fm.reset()
    assert os.path.exists(ck), "preemption left no checkpoint"

    resumed = lgb.train(dict(params), lgb.Dataset(X, y),
                        num_boost_round=8).model_to_string()
    assert resumed == straight, \
        "resumed model is NOT bit-identical to the uninterrupted run"
    print("# step 1 OK: kill@4 -> resume -> bit-identical "
          "model_to_string (exit code contract honored)")


def step2_corruption(tmpdir) -> None:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience import checkpoint as ckpt
    from lightgbm_tpu.resilience import faults as fm
    from lightgbm_tpu.resilience.errors import (CorruptCheckpointError,
                                                CorruptModelError)
    from lightgbm_tpu.model_io import load_model_from_string

    X, y = _fixture()
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, y),
                    num_boost_round=4)
    ck = os.path.join(tmpdir, "corrupt.ckpt")
    fm.install(fm.FaultPlan(corrupt_checkpoint_byte=200))
    try:
        ckpt.save_checkpoint(bst, ck)
    finally:
        fm.reset()
    try:
        ckpt.load_checkpoint(ck)
        raise AssertionError("corrupt checkpoint was ACCEPTED")
    except CorruptCheckpointError as e:
        assert e.offset is not None
    # truncated model file -> structured error naming a byte offset
    s = bst.model_to_string()
    frag = s[:s.index("end of trees") - 30]
    try:
        load_model_from_string(frag)
        raise AssertionError("truncated model was ACCEPTED")
    except CorruptModelError as e:
        assert e.offset is not None and e.offset > 0
    print("# step 2 OK: corrupt checkpoint + truncated model both "
          "rejected with structured errors (byte offsets named)")


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
        return resp.read().decode()


def _family(text: str, name: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def step3_serve_degradation() -> None:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience import faults as fm
    from lightgbm_tpu.resilience.errors import (CircuitOpenError,
                                                DeadlineExceeded,
                                                ServerOverloaded,
                                                TransientServeError)
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import ModelServer

    X, y = _fixture(400)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, y),
                    num_boost_round=3)
    registry = ModelRegistry()
    registry.load("m", booster=bst)
    direct = registry.get("m").model.predict(X[:4])

    async def run() -> None:
        # deadline: an effectively-zero budget fails fast, batcher
        # never spends device time on it. Breaker knobs are fixed at
        # construction (the per-model breaker latches its threshold on
        # first use).
        srv = ModelServer(registry, deadline_ms=1e-6,
                          breaker_threshold=3, breaker_reset_s=60.0)
        ep = srv.start_metrics_endpoint(0)
        try:
            await srv.predict("m", X[:200])
            raise AssertionError("expired deadline was served")
        except DeadlineExceeded:
            pass

        # load shed: a slow dispatch occupies the queue; the second
        # concurrent arrival exceeds the row bound and is shed with
        # retry-after semantics
        srv.max_queue_rows = 64
        srv.deadline_s = 0.0
        fm.install(fm.FaultPlan(serve_slow_ms=120))
        first = asyncio.ensure_future(srv.predict("m", X[:60]))
        await asyncio.sleep(0.02)
        try:
            await srv.predict("m", X[:60])
            raise AssertionError("overload was admitted")
        except ServerOverloaded as e:
            assert e.retry_after_s > 0
        await first
        fm.reset()

        # retry-to-success: one injected transient pack fault, answer
        # still bit-exact
        fm.install(fm.FaultPlan(serve_predict_failures=1))
        srv.retry_max, srv.retry_backoff_s = 2, 0.001
        out = await srv.predict("m", X[:4])
        assert np.array_equal(np.asarray(out), np.asarray(direct)), \
            "retried answer is not bit-identical to direct predict"
        fm.reset()

        # breaker: persistent faults trip it; fail-fast while open
        fm.install(fm.FaultPlan(serve_predict_failures=100))
        srv.retry_max = 0
        for _ in range(3):
            try:
                await srv.predict("m", X[:4])
            except TransientServeError:
                pass
        try:
            await srv.predict("m", X[:4])
            raise AssertionError("open breaker admitted a request")
        except CircuitOpenError as e:
            assert e.retry_after_s > 0
        fm.reset()

        # every degradation event must be visible in a REAL scrape
        text = _scrape(ep.port)
        for fam, floor in (
                ("lgbmtpu_resilience_deadline_exceeded_total", 1),
                ("lgbmtpu_resilience_load_shed_total", 1),
                ("lgbmtpu_resilience_retries_total", 1),
                ("lgbmtpu_resilience_breaker_open_total", 1),
                ("lgbmtpu_resilience_breaker_rejected_total", 1),
                ("lgbmtpu_resilience_breakers_open", 1)):
            got = _family(text, fam)
            assert got >= floor, \
                f"/metrics family {fam} = {got}, expected >= {floor}"
        await srv.close()

    asyncio.run(run())
    print("# step 3 OK: deadline fail-fast, load shed w/ retry-after, "
          "transient retry (bit-exact), breaker trip — all observed "
          "via /metrics lgbmtpu_resilience_* families")


def main() -> int:
    import tempfile
    with tempfile.TemporaryDirectory() as tmpdir:
        step1_kill_resume(tmpdir)
        step2_corruption(tmpdir)
        step3_serve_degradation()
    print("# resilience chaos validator OK (3/3 steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
