"""Native C++ host runtime tests: parser + binning parity with the
NumPy fallback (the two paths must agree bit-for-bit)."""

import os

import numpy as np
import pytest

from lightgbm_tpu import native
from lightgbm_tpu.binning import BinMapper
from lightgbm_tpu.io.text_loader import load_svmlight_or_csv

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


@pytest.fixture
def columns(rng):
    return {
        "normal": rng.randn(5000),
        "zero_heavy": np.concatenate([np.zeros(2000),
                                      rng.gamma(2, 2, 3000)]),
        "with_nan": np.concatenate([rng.randn(3000), [np.nan] * 200]),
        "few_distinct": np.round(rng.randn(8000) * 2),
        "constant": np.full(500, 3.25),
        "negative": -np.abs(rng.randn(4000)),
    }


def test_find_bounds_parity(columns):
    for name, vals in columns.items():
        for zam in (False, True):
            for max_bin in (15, 63, 255):
                m = BinMapper()
                # force the python path by disabling native inside fit
                os.environ["LIGHTGBM_TPU_NO_NATIVE"] = "1"
                try:
                    native_state = native._tried, native._lib
                    native._tried, native._lib = True, None
                    m.fit(vals.copy(), max_bin=max_bin, min_data_in_bin=3,
                          zero_as_missing=zam)
                finally:
                    del os.environ["LIGHTGBM_TPU_NO_NATIVE"]
                    native._tried, native._lib = native_state
                nb = native.find_numerical_bounds(
                    vals, max_bin, 3, m.missing_type, zam)
                assert nb is not None
                np.testing.assert_array_equal(
                    nb, m.bin_upper_bound,
                    err_msg=f"bounds mismatch: {name} zam={zam} "
                            f"max_bin={max_bin}")


def test_transform_parity(columns):
    for name, vals in columns.items():
        m = BinMapper().fit(vals.copy(), max_bin=63, min_data_in_bin=3)
        nat = native.transform_column(vals, m.bin_upper_bound,
                                      m.missing_type, m.default_bin,
                                      m.num_bins)
        ref = m.transform(vals)  # may itself use native for big arrays
        np.testing.assert_array_equal(nat, ref, err_msg=name)


def test_transform_matrix_parity(rng):
    data = rng.randn(3000, 12)
    data[rng.rand(3000, 12) < 0.05] = np.nan
    mappers = [BinMapper().fit(data[:, j], max_bin=63) for j in range(12)]
    out = native.transform_matrix(np.ascontiguousarray(data), mappers,
                                  np.uint8)
    assert out is not None
    for j, m in enumerate(mappers):
        np.testing.assert_array_equal(out[j], m.transform(data[:, j]),
                                      err_msg=f"col {j}")


def test_parse_tsv_parity(tmp_path, rng):
    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(float)
    path = tmp_path / "d.tsv"
    with open(path, "w") as fh:
        for label, row in zip(y, X):
            fh.write("\t".join([f"{label:g}"] + [f"{v:.8g}" for v in row])
                     + "\n")
    data, label = native.parse_file(str(path), 0, False)
    assert data.shape == (300, 5)
    np.testing.assert_allclose(label, y)
    np.testing.assert_allclose(data, X, rtol=1e-6)


def test_parse_csv_with_missing(tmp_path):
    path = tmp_path / "d.csv"
    path.write_text("1,0.5,NA,2.0\n0,nan,1.5,\n1,3.0,?,4.0\n")
    data, label = native.parse_file(str(path), 0, False)
    np.testing.assert_allclose(label, [1, 0, 1])
    assert np.isnan(data[0, 1]) and np.isnan(data[1, 0])
    assert np.isnan(data[1, 2]) and np.isnan(data[2, 1])
    np.testing.assert_allclose(data[2], [3.0, np.nan, 4.0])


def test_parse_libsvm(tmp_path):
    path = tmp_path / "d.svm"
    path.write_text("1 0:0.5 3:2.0\n0 1:1.5\n1 0:3.0 2:1.0 3:4.0\n")
    data, label = native.parse_file(str(path), 0, False)
    assert data.shape == (3, 4)
    np.testing.assert_allclose(label, [1, 0, 1])
    np.testing.assert_allclose(data[0], [0.5, 0, 0, 2.0])
    np.testing.assert_allclose(data[1], [0, 1.5, 0, 0])


def test_parse_header_and_label_column(tmp_path):
    path = tmp_path / "d.csv"
    path.write_text("a,b,target\n0.1,0.2,1\n0.3,0.4,0\n")
    data, label = native.parse_file(str(path), 2, True)
    np.testing.assert_allclose(label, [1, 0])
    np.testing.assert_allclose(data, [[0.1, 0.2], [0.3, 0.4]])


def test_loader_uses_native_and_matches_python(tmp_path, rng):
    X = rng.randn(500, 4)
    y = (X[:, 0] > 0).astype(float)
    path = tmp_path / "d.tsv"
    with open(path, "w") as fh:
        for label, row in zip(y, X):
            fh.write("\t".join([f"{label:g}"] + [f"{v:.8g}" for v in row])
                     + "\n")
    d1, l1, _, _ = load_svmlight_or_csv(str(path), {})
    native_state = native._tried, native._lib
    try:
        native._tried, native._lib = True, None
        d2, l2, _, _ = load_svmlight_or_csv(str(path), {})
    finally:
        native._tried, native._lib = native_state
    np.testing.assert_allclose(d1, d2)
    np.testing.assert_allclose(l1, l2)


def test_parse_error_path(tmp_path):
    path = tmp_path / "bad.csv"
    path.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        native.parse_file(str(path), 0, False)


def test_end_to_end_training_with_native(rng):
    import lightgbm_tpu as lgb
    X = rng.randn(2000, 10)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.5).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    preds = bst.predict(X)
    assert preds[y == 1].mean() > preds[y == 0].mean() + 0.2
