#!/usr/bin/env python
"""Chaos validator for elastic continual training (ISSUE 12).

Drives the REAL code paths end-to-end with the deterministic fault
plan (lightgbm_tpu/resilience/faults.py) — the acceptance scenario of
the elastic-continual PR, kept honest in CI:

1. **Kill -> resume on a RESIZED mesh** — ``resize_at_iter`` preempts
   a 1-shard run at iteration k (exit 75); the re-run restores the
   checkpoint onto a 2-shard mesh through the drift-validated rejoin
   (resilience/elastic.py gate_rejoin), and the finished model's
   predictions are bit-identical to the never-preempted run. The
   resize is observed as a counted event (``resilience/mesh_resizes``).
2. **Poisoned generation -> automatic rollback, serve isolation** —
   a continual loop over fresh chunks accepts a healthy generation
   into a live ``ModelRegistry``, then ingests a poisoned chunk
   (NaN labels -> NaN eval) and a quality-regressed chunk (labels
   blown up -> eval spike): BOTH are rolled back by the eval anomaly
   gate, the registry still serves the exact last-good entry (the
   rejected generations were never observable from the serve side),
   and a healthy follow-up chunk extends the last-good model.
3. **Live /metrics scrape** — against the server wrapping that same
   registry: every ``lgbmtpu_continual_*`` family is present in a real
   HTTP scrape and the document passes the OpenMetrics lint
   (tools/check_metrics_endpoint.py).

Exit 0 = all steps passed. Wired into the quick verification tier via
tests/test_resilience.py (TestToolsWiring).
"""

import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _fixture(n=264, f=6, seed=3):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] * 2.0 - X[:, 1] + 0.1 * r.randn(n)).astype(np.float32)
    return X, y


def step1_resize_resume(tmpdir) -> None:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.resilience import faults as fm
    from lightgbm_tpu.resilience.errors import EXIT_PREEMPTED

    r = np.random.RandomState(0)
    X = r.randn(264, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.4).astype(np.float32)
    ck = os.path.join(tmpdir, "resize.ckpt")
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "tpu_checkpoint_path": ck, "tpu_num_shards": 1}
    straight = lgb.train(dict(params), lgb.Dataset(X, y),
                         num_boost_round=8)
    p_straight = straight.predict(X)
    if os.path.exists(ck):  # only written if a snapshot knob fired
        os.remove(ck)

    fm.install(fm.FaultPlan(resize_at_iter=3))
    try:
        lgb.train(dict(params), lgb.Dataset(X, y), num_boost_round=8)
        raise AssertionError("injected resize preemption did not exit")
    except SystemExit as e:
        assert e.code == EXIT_PREEMPTED, \
            f"resize preemption exit code {e.code} != {EXIT_PREEMPTED}"
    finally:
        fm.reset()
    assert os.path.exists(ck), "resize preemption left no checkpoint"

    before = int(global_metrics.counters.get("resilience/mesh_resizes",
                                             0))
    resized = dict(params, tpu_num_shards=2)
    resumed = lgb.train(dict(resized), lgb.Dataset(X, y),
                        num_boost_round=8)
    assert resumed.current_iteration() == 8
    # quality parity with the unresized run: the sharded histogram
    # reduce carries ulp-level f32 ordering noise across mesh widths,
    # so the contract is the mesh-parity tolerance the distributed
    # suite pins (tests/test_distributed.py), not bit equality
    np.testing.assert_allclose(resumed.predict(X), p_straight,
                               rtol=1e-4, atol=1e-4)
    resizes = int(global_metrics.counters.get("resilience/mesh_resizes",
                                              0)) - before
    assert resizes == 1, \
        f"mesh resize not counted as an event (delta {resizes})"
    print("# step 1 OK: kill@3 -> resume on 2-shard mesh -> "
          "drift-validated rejoin, quality parity with the unresized "
          "run, resize counted")


def step2_rollback_isolation(registry) -> "object":
    import lightgbm_tpu as lgb

    params = {"objective": "regression", "num_leaves": 7, "metric": "l2",
              "verbosity": -1, "tpu_continual_rounds": 4,
              "tpu_continual_eval_fraction": 0.25}
    trainer = lgb.ContinualTrainer(params, num_features=6,
                                   registry=registry, serve_name="m")

    X0, y0 = _fixture(seed=0)
    r0 = trainer.push_rows(X0, label=y0).step()
    assert r0.accepted, "healthy generation was rejected"
    served = registry.get("m")
    probe = X0[:8]
    p_good = served.predict_raw(probe)

    # NaN labels -> NaN held-out eval -> "nan" rollback
    X1, y1 = _fixture(seed=1)
    r1 = trainer.push_rows(X1, label=y1 * np.nan).step()
    assert not r1.accepted and r1.reason == "nan", \
        f"NaN generation not rolled back ({r1.reason!r})"
    # labels blown up -> eval spike vs cross-generation history
    X2, y2 = _fixture(seed=2)
    r2 = trainer.push_rows(X2, label=y2 * 1000.0).step()
    assert not r2.accepted and r2.reason == "spike", \
        f"regressed generation not rolled back ({r2.reason!r})"

    # the serve side never saw either rejected generation
    assert registry.get("m") is served, \
        "registry entry was replaced by a rejected generation"
    assert np.array_equal(served.predict_raw(probe), p_good), \
        "served predictions changed after rejected generations"
    assert trainer.model_iterations == 4, \
        "last-good model did not stand after rollbacks"

    # a healthy chunk extends the LAST-GOOD model and hot-swaps
    X3, y3 = _fixture(seed=5)
    r3 = trainer.push_rows(X3, label=y3).step()
    assert r3.accepted and trainer.model_iterations == 8
    assert registry.get("m") is not served, \
        "accepted generation did not hot-swap"
    s = trainer.summary()
    assert (s["generations"], s["rollbacks"]) == (4, 2), s
    print("# step 2 OK: NaN + spike generations rolled back, serve "
          "registry never exposed them, healthy generation extended "
          "last-good and hot-swapped")
    return trainer


CONTINUAL_FAMILIES = (
    "lgbmtpu_continual_generations_total",
    "lgbmtpu_continual_accepted_total",
    "lgbmtpu_continual_rollbacks_total",
    "lgbmtpu_continual_swaps_total",
    "lgbmtpu_continual_swap_seconds_total",
    "lgbmtpu_continual_last_swap_seconds",
    "lgbmtpu_continual_model_iterations",
    "lgbmtpu_continual_retained_snapshots",
    "lgbmtpu_continual_resumes_total",
    "lgbmtpu_continual_mesh_resizes_total",
)


def step3_metrics_scrape(registry) -> None:
    import asyncio

    from lightgbm_tpu.serve.server import ModelServer

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import check_metrics_endpoint

    async def run() -> str:
        srv = ModelServer(registry)
        ep = srv.start_metrics_endpoint(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ep.port}/metrics",
                    timeout=5) as resp:
                return resp.read().decode()
        finally:
            await srv.close()

    text = asyncio.run(run())
    errors, families = check_metrics_endpoint.validate_exposition(text)
    assert not errors, f"OpenMetrics lint errors: {errors[:5]}"
    missing = [f for f in CONTINUAL_FAMILIES if f not in families]
    assert not missing, f"missing lgbmtpu_continual_* families: {missing}"
    print(f"# step 3 OK: live /metrics scrape carries all "
          f"{len(CONTINUAL_FAMILIES)} lgbmtpu_continual_* families "
          "(lint clean)")


def main() -> int:
    import tempfile

    from lightgbm_tpu.serve.registry import ModelRegistry
    with tempfile.TemporaryDirectory() as tmpdir:
        step1_resize_resume(tmpdir)
        registry = ModelRegistry()
        trainer = step2_rollback_isolation(registry)
        # step 1's resume counters fold into the continual summary the
        # exporter publishes — refresh it before the scrape
        trainer._publish()
        step3_metrics_scrape(registry)
    print("# continual chaos validator OK (3/3 steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
