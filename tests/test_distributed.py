"""Distributed (data-parallel) training over the virtual 8-device CPU mesh
(ref strategy: tests/distributed/_test_distributed.py DistributedMockup —
there via N localhost CLI processes + sockets; here via jax.sharding over
a forced multi-device host platform, which exercises the same program the
TPU mesh runs)."""

import numpy as np
import jax
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc
from tests.conftest import make_binary, make_regression


@pytest.fixture(autouse=True)
def _require_multi_device():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (XLA_FLAGS host platform count)")


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_data_parallel_binary_quality():
    X, y = make_binary(2000)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "tree_learner": "data",
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "verbosity": -1},
                    dtrain, num_boost_round=20)
    assert _auc(y, bst.predict(X)) > 0.9


def test_data_parallel_matches_serial():
    """Distributed vs single-device training must agree (ref:
    _test_distributed.py:168 accuracy + prediction agreement check)."""
    X, y = make_regression(1024)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": 7}
    serial = lgb.train(dict(params), lgb.Dataset(X, label=y),
                       num_boost_round=10)
    parallel = lgb.train({**params, "tree_learner": "data"},
                         lgb.Dataset(X, label=y), num_boost_round=10)
    ps = serial.predict(X)
    pp = parallel.predict(X)
    # identical math; tolerance covers cross-shard reduction order
    np.testing.assert_allclose(pp, ps, rtol=1e-3, atol=1e-3)


def test_data_parallel_sharded_arrays():
    X, y = make_binary(512)
    dtrain = lgb.Dataset(X, label=y)
    bst = lgb.Booster({"objective": "binary", "tree_learner": "data",
                       "num_leaves": 7, "verbosity": -1}, dtrain)
    gbdt = bst._gbdt
    assert gbdt.mesh.size == 8
    # bins sharded along rows (axis 1)
    sharding = gbdt.bins_fm.sharding
    spec = sharding.spec
    assert spec[1] == "data"
    bst.update()
    assert bst.current_iteration() == 1


def test_data_parallel_num_shards_param():
    X, y = make_binary(512)
    bst = lgb.Booster({"objective": "binary", "tpu_num_shards": 4,
                       "num_leaves": 7, "verbosity": -1},
                      lgb.Dataset(X, label=y))
    assert bst._gbdt.mesh.size == 4
    bst.update()


def test_voting_and_feature_learner_accepted():
    X, y = make_binary(512)
    for tl in ("voting", "feature"):
        bst = lgb.train({"objective": "binary", "tree_learner": tl,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=3)
        assert bst.num_trees() == 3
