"""Linear-tree leaf models: per-leaf regularized weighted least squares.

(ref: src/treelearner/linear_tree_learner.cpp:8,345 — after the tree
structure is grown, every leaf gets a linear model over the numerical
features on its root path, fit by solving (X'HX + lambda I) w = -X'g,
the Newton step on this iteration's gradients. Eigen there; NumPy here —
both are host-side solves over small per-leaf systems.)
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tree import Tree, _CATEGORICAL_MASK


def _path_features(tree: Tree) -> List[List[int]]:
    """Numerical split features on each leaf's root path, in path order."""
    # parent of each internal node
    parent = np.full(tree.num_internal, -1, np.int32)
    for node in range(tree.num_internal):
        for child in (tree.left_child[node], tree.right_child[node]):
            if child >= 0:
                parent[child] = node
    out: List[List[int]] = []
    for leaf in range(tree.num_leaves):
        feats: List[int] = []
        node = tree.leaf_parent[leaf]
        while node >= 0:
            if not (tree.decision_type[node] & _CATEGORICAL_MASK):
                f = int(tree.split_feature[node])
                if f not in feats:
                    feats.append(f)
            node = parent[node]
        feats.reverse()
        out.append(feats)
    return out


def fit_linear_models(tree: Tree, raw_data: np.ndarray,
                      row_leaf: np.ndarray, grad: np.ndarray,
                      hess: np.ndarray, sample_mask: np.ndarray,
                      linear_lambda: float) -> None:
    """Fit leaf linear models in place (ref: LinearTreeLearner::
    CalculateLinear linear_tree_learner.cpp:345). Leaves whose system is
    degenerate keep a constant model (coeffs empty, const = leaf_value)."""
    if tree.num_internal == 0:
        tree.is_linear = True
        tree.leaf_const = tree.leaf_value.copy()
        return
    path_feats = _path_features(tree)
    tree.is_linear = True
    tree.leaf_const = tree.leaf_value.copy()
    tree.leaf_coeff = [np.zeros(0)] * tree.num_leaves
    tree.leaf_features = [[] for _ in range(tree.num_leaves)]

    sel = sample_mask > 0
    for leaf in range(tree.num_leaves):
        feats = path_feats[leaf]
        rows = np.flatnonzero((row_leaf == leaf) & sel)
        if not feats or rows.size < len(feats) + 2:
            continue
        x = raw_data[np.ix_(rows, feats)]
        ok = ~np.isnan(x).any(axis=1)
        if ok.sum() < len(feats) + 2:
            continue
        x = x[ok]
        g = grad[rows][ok].astype(np.float64)
        h = hess[rows][ok].astype(np.float64)
        # design with bias column; Newton system (X'HX + lam I)w = -X'g
        xb = np.hstack([x, np.ones((x.shape[0], 1))])
        xth = xb * h[:, None]
        a = xth.T @ xb
        k = len(feats)
        a[np.arange(k), np.arange(k)] += linear_lambda
        b = -(xb.T @ g)
        try:
            w = np.linalg.solve(a, b)
        except np.linalg.LinAlgError:
            continue
        if not np.all(np.isfinite(w)):
            continue
        tree.leaf_features[leaf] = list(feats)
        tree.leaf_coeff[leaf] = w[:-1]
        tree.leaf_const[leaf] = float(w[-1])
