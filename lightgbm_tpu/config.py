"""Parameter / config system.

TPU-native re-implementation of the reference config layer
(ref: include/LightGBM/config.h:41, src/io/config_auto.cpp alias tables).
A single flat dict of canonical parameters with alias resolution, typed
defaults, and `key=value` string parsing for CLI/config-file use
(ref: Config::KV2Map include/LightGBM/config.h:101).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Alias table (canonical name -> aliases), mirroring the semantics of the
# generated table in the reference (src/io/config_auto.cpp).
# ---------------------------------------------------------------------------
_ALIASES: Dict[str, List[str]] = {
    "config": ["config_file"],
    "task": ["task_type"],
    "objective": ["objective_type", "app", "application", "loss"],
    "boosting": ["boosting_type", "boost"],
    "data_sample_strategy": [],
    "data": ["train", "train_data", "train_data_file", "data_filename"],
    "valid": ["test", "valid_data", "valid_data_file", "test_data", "test_data_file", "valid_filenames"],
    "num_iterations": [
        "num_iteration", "n_iter", "num_tree", "num_trees", "num_round", "num_rounds",
        "nrounds", "num_boost_round", "n_estimators", "max_iter",
    ],
    "learning_rate": ["shrinkage_rate", "eta"],
    "num_leaves": ["num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"],
    "tree_learner": ["tree", "tree_type", "tree_learner_type"],
    "num_threads": ["num_thread", "nthread", "nthreads", "n_jobs"],
    "device_type": ["device"],
    "seed": ["random_seed", "random_state"],
    "deterministic": [],
    "force_col_wise": [],
    "force_row_wise": [],
    "histogram_pool_size": ["hist_pool_size"],
    "max_depth": [],
    "min_data_in_leaf": ["min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"],
    "min_sum_hessian_in_leaf": [
        "min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight",
    ],
    "bagging_fraction": ["sub_row", "subsample", "bagging"],
    "pos_bagging_fraction": ["pos_sub_row", "pos_subsample", "pos_bagging"],
    "neg_bagging_fraction": ["neg_sub_row", "neg_subsample", "neg_bagging"],
    "bagging_freq": ["subsample_freq"],
    "bagging_seed": ["bagging_fraction_seed"],
    "bagging_by_query": [],
    "feature_fraction": ["sub_feature", "colsample_bytree"],
    "feature_fraction_bynode": ["sub_feature_bynode", "colsample_bynode"],
    "feature_fraction_seed": [],
    "extra_trees": ["extra_tree"],
    "extra_seed": [],
    "early_stopping_round": [
        "early_stopping_rounds", "early_stopping", "n_iter_no_change",
    ],
    "early_stopping_min_delta": [],
    "first_metric_only": [],
    "max_delta_step": ["max_tree_output", "max_leaf_output"],
    "lambda_l1": ["reg_alpha", "l1_regularization"],
    "lambda_l2": ["reg_lambda", "lambda", "l2_regularization"],
    "linear_lambda": [],
    "min_gain_to_split": ["min_split_gain"],
    "drop_rate": ["rate_drop"],
    "max_drop": [],
    "skip_drop": [],
    "xgboost_dart_mode": [],
    "uniform_drop": [],
    "drop_seed": [],
    "top_rate": [],
    "other_rate": [],
    "min_data_per_group": [],
    "max_cat_threshold": [],
    "cat_l2": [],
    "cat_smooth": [],
    "max_cat_to_onehot": [],
    "top_k": ["topk"],
    "monotone_constraints": ["mc", "monotone_constraint", "monotonic_cst"],
    "monotone_constraints_method": ["monotone_constraining_method", "mc_method"],
    "monotone_penalty": ["monotone_splits_penalty", "ms_penalty", "mc_penalty"],
    "feature_contri": ["feature_contrib", "fc", "fp", "feature_penalty"],
    "forcedsplits_filename": ["fs", "forced_splits_filename", "forced_splits_file", "forced_splits"],
    "refit_decay_rate": [],
    "cegb_tradeoff": [],
    "cegb_penalty_split": [],
    "cegb_penalty_feature_lazy": [],
    "cegb_penalty_feature_coupled": [],
    "path_smooth": [],
    "interaction_constraints": [],
    "verbosity": ["verbose"],
    "input_model": ["model_input", "model_in"],
    "output_model": ["model_output", "model_out"],
    "saved_feature_importance_type": [],
    "snapshot_freq": ["save_period"],
    "linear_tree": ["linear_trees"],
    "max_bin": ["max_bins"],
    "max_bin_by_feature": [],
    "min_data_in_bin": [],
    "bin_construct_sample_cnt": ["subsample_for_bin"],
    "data_random_seed": ["data_seed"],
    "is_enable_sparse": ["is_sparse", "enable_sparse", "sparse"],
    "enable_bundle": ["is_enable_bundle", "bundle"],
    "max_conflict_rate": [],
    "use_missing": [],
    "zero_as_missing": [],
    "feature_pre_filter": [],
    "pre_partition": ["is_pre_partition"],
    "two_round": ["two_round_loading", "use_two_round_loading"],
    "header": ["has_header"],
    "label_column": ["label"],
    "weight_column": ["weight"],
    "group_column": ["group", "group_id", "query_column", "query", "query_id"],
    "ignore_column": ["ignore_feature", "blacklist"],
    "categorical_feature": ["cat_feature", "categorical_column", "cat_column"],
    "forcedbins_filename": [],
    "save_binary": ["is_save_binary", "is_save_binary_file"],
    "precise_float_parser": [],
    "parser_config_file": [],
    "start_iteration_predict": [],
    "num_iteration_predict": [],
    "predict_raw_score": ["is_predict_raw_score", "predict_rawscore", "raw_score"],
    "predict_leaf_index": ["is_predict_leaf_index", "leaf_index"],
    "predict_contrib": ["is_predict_contrib", "contrib"],
    "predict_disable_shape_check": [],
    "pred_early_stop": [],
    "pred_early_stop_freq": [],
    "pred_early_stop_margin": [],
    "output_result": ["predict_result", "prediction_result", "predict_name", "pred_name", "name_pred"],
    "convert_model_language": [],
    "convert_model": ["convert_model_file"],
    "objective_seed": [],
    "num_class": ["num_classes"],
    "is_unbalance": ["unbalance", "unbalanced_sets"],
    "scale_pos_weight": [],
    "sigmoid": [],
    "boost_from_average": [],
    "reg_sqrt": [],
    "alpha": [],
    "fair_c": [],
    "poisson_max_delta_step": [],
    "tweedie_variance_power": [],
    "lambdarank_truncation_level": [],
    "lambdarank_norm": [],
    "label_gain": [],
    "lambdarank_position_bias_regularization": [],
    "metric": ["metrics", "metric_types"],
    "metric_freq": ["output_freq"],
    "is_provide_training_metric": ["training_metric", "is_training_metric", "train_metric"],
    "eval_at": ["ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"],
    "multi_error_top_k": [],
    "auc_mu_weights": [],
    "num_machines": ["num_machine"],
    "local_listen_port": ["local_port", "port"],
    "time_out": [],
    "machine_list_filename": ["machine_list_file", "machine_list", "mlist"],
    "machines": ["workers", "nodes"],
    "gpu_platform_id": [],
    "gpu_device_id": [],
    "gpu_use_dp": [],
    "num_gpu": [],
    "use_quantized_grad": [],
    "num_grad_quant_bins": [],
    "quant_train_renew_leaf": [],
    "stochastic_rounding": [],
    # TPU-specific knobs (new in this framework)
    "trace_output": ["trace_file", "trace_path"],
    "tpu_hist_dtype": [],
    "tpu_num_shards": [],
    "tpu_donate_buffers": [],
    "tpu_wave_max": [],
    "tpu_hist_precision": [],
    "tpu_hist_impl": [],
    "tpu_hist_reduce": ["hist_reduce"],
    "tpu_sparse_hist": [],
    "tpu_bin_pack": ["bin_pack"],
    "tpu_stream": ["stream", "out_of_core"],
    "tpu_stream_slab_rows": ["stream_slab_rows", "slab_rows"],
    "tpu_fused_grad": ["fused_grad"],
    "tpu_wave_subtract": [],
    "deterministic_hist": ["tpu_deterministic_hist"],
    "tpu_dart_fused_max_bytes": [],
    "tpu_predict_chunk": ["predict_chunk", "predict_chunk_rows"],
    "tpu_shap": ["shap", "pred_contrib_device", "tpu_pred_contrib"],
    "tpu_preflight": ["preflight", "memory_preflight"],
    "tpu_health": ["health", "training_health"],
    "tpu_health_every": ["health_every", "health_check_every"],
    "tpu_compile_cache": ["compile_cache", "persistent_compile_cache"],
    "tpu_compile_cache_dir": ["compile_cache_dir"],
    "tpu_profile": ["profile", "device_profile"],
    "tpu_profile_window": ["profile_window", "profile_iters"],
    # resilience knobs (resilience/ subsystem)
    "tpu_checkpoint_every": ["checkpoint_every", "checkpoint_freq"],
    "tpu_checkpoint_path": ["checkpoint_path", "checkpoint_file"],
    "tpu_elastic_resume": ["elastic_resume"],
    "tpu_watchdog_deadline_s": ["watchdog_deadline_s", "watchdog_deadline"],
    "tpu_continual_rounds": ["continual_rounds"],
    "tpu_continual_retain": ["continual_retain", "continual_snapshots"],
    "tpu_continual_eval_fraction": ["continual_eval_fraction"],
    "tpu_continual_mode": ["continual_mode"],
    # serving knobs (serve/ subsystem)
    "serve_max_batch_rows": ["serve_max_batch"],
    "serve_max_wait_ms": ["serve_max_wait"],
    "serve_lowlat_max_rows": ["serve_lowlat_rows"],
    "serve_cache_bytes": ["serve_pack_budget_bytes"],
    "serve_request_rows": [],
    "serve_metrics_port": ["metrics_port"],
    "serve_deadline_ms": ["serve_deadline"],
    "serve_max_queue_rows": ["serve_queue_rows"],
    "serve_retry_max": ["serve_retries"],
    "serve_retry_backoff_ms": [],
    "serve_breaker_threshold": ["serve_breaker_failures"],
    "serve_breaker_reset_s": ["serve_breaker_reset"],
    "serve_artifact_dir": ["artifact_dir", "serve_artifacts_dir"],
    # serving-fleet knobs (serve/fleet.py)
    "serve_fleet_replicas": ["fleet_replicas"],
    "serve_probe_interval_ms": ["fleet_probe_interval_ms"],
    "serve_hedge_ms": ["fleet_hedge_ms"],
}

_ALIAS_TO_CANONICAL: Dict[str, str] = {}
for _canon, _al in _ALIASES.items():
    _ALIAS_TO_CANONICAL[_canon] = _canon
    for _a in _al:
        _ALIAS_TO_CANONICAL.setdefault(_a, _canon)

# keys already warned about as unsupported, process-wide (Booster and
# Dataset both build Configs from overlapping dicts; warn once per key)
_WARNED_UNSUPPORTED: set = set()

# Objective aliases (ref: config.h:136-160 objective name variants).
_OBJECTIVE_ALIASES = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "none",
    "null": "none",
    "custom": "none",
    "na": "none",
}

# Metric aliases (ref: src/metric/metric.cpp:22-134).
_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2", "regression_l2": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg", "xendcg": "ndcg",
    "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "average_precision": "average_precision",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "r2": "r2",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_BOOSTING_ALIASES = {
    "gbdt": "gbdt", "gbrt": "gbdt",
    "dart": "dart",
    "rf": "rf", "random_forest": "rf",
    "goss": "goss",  # legacy alias: boosting=goss => gbdt + goss sampling
}


@dataclasses.dataclass
class Config:
    """Canonical training configuration (ref: include/LightGBM/config.h:41)."""

    # Core
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data_sample_strategy: str = "bagging"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: int = 0
    deterministic: bool = False

    # Learning control
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    bagging_by_query: bool = False
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    early_stopping_min_delta: float = 0.0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: Any = None
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: Any = None
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: Any = None
    cegb_penalty_feature_coupled: Any = None
    path_smooth: float = 0.0
    interaction_constraints: Any = None
    verbosity: int = 1
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    saved_feature_importance_type: int = 0
    snapshot_freq: int = -1
    linear_tree: bool = False

    # Dataset
    max_bin: int = 255
    max_bin_by_feature: Any = None
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Any = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False

    # IO for train/predict
    data: str = ""
    valid: Any = None
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    output_result: str = "LightGBM_predict_result.txt"
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # Objective
    objective_seed: int = 5
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: Any = None
    lambdarank_position_bias_regularization: float = 0.0

    # Metric
    metric: Any = None
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: Any = None
    multi_error_top_k: int = 1
    auc_mu_weights: Any = None

    # Network
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # GPU compat (accepted, ignored on TPU)
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    num_gpu: int = 1

    # Quantized-gradient training (ref: config.h use_quantized_grad)
    use_quantized_grad: bool = False
    num_grad_quant_bins: int = 4
    quant_train_renew_leaf: bool = False
    stochastic_rounding: bool = True

    # Observability: write a Chrome trace-event JSON of training spans
    # to this path at exit (param twin of LGBM_TPU_TRACE; obs/trace.py,
    # validated by tools/check_trace.py)
    trace_output: str = ""

    # TPU-specific
    tpu_hist_dtype: str = "float32"
    tpu_num_shards: int = 0  # 0 = use all local devices for data-parallel learner
    tpu_donate_buffers: bool = True
    # waved leaf-wise growth: batch histogram builds of up to this many
    # splits into one multi-leaf pass (0 = exact per-split builds).
    # Wave sizes follow a frontier-proportional schedule — see
    # learner._wave_schedule — so early splits stay near-exact; the cap
    # only bounds the LATE waves. 42 = the multi-leaf kernel's slot
    # count (128 MXU lanes // 3 channels); ~13 full-data histogram
    # passes per 255-leaf tree instead of 254, at quality parity on
    # binary/regression/ranking (tests/test_waved.py; parity-gated vs
    # the reference in tests/test_consistency.py's waved tier).
    #
    # Default -1 = AUTO: 42 for single-output models, 0 (exact) for
    # multiclass. Measured (round 5): the waved code path at wave size 1
    # is BIT-IDENTICAL to the exact grower, but any batching >= 2
    # perturbs softmax split order enough to drift multiclass logloss
    # calibration +0.08..+0.13 on the reference multiclass example
    # (auc_mu ordering stays better than the reference throughout) —
    # softmax's cross-class coupling makes tree structure order-critical,
    # so multiclass defaults to exact order. Set tpu_wave_max=42
    # explicitly to trade that calibration for ~20x fewer histogram
    # passes on large multiclass data.
    tpu_wave_max: int = -1
    # MXU precision of the histogram one-hot contraction: "default" =
    # single bf16 pass with f32 accumulation (the one-hot operand is
    # exact in bf16; the grad/hess operand is rounded to 8 mantissa
    # bits — noise far below the gradient-quantization the reference
    # itself ships with use_quantized_grad), "high" = 3-pass, "highest"
    # = 6-pass f32 emulation. On CPU (tests) every mode is exact f32.
    # Measured on the TPU chip: "default" matches "highest" AUC to
    # ~1e-3 at Higgs shape while cutting iteration time ~2x.
    tpu_hist_precision: str = "default"
    # histogram kernel implementation: "auto" = pallas on TPU backends /
    # one-hot XLA contraction elsewhere; "pallas" / "xla" force one
    # (pallas on CPU runs in interpret mode — tests use this to exercise
    # the kernel + its shard_map mesh wrapper without a chip)
    tpu_hist_impl: str = "auto"
    # data-parallel histogram reduction (parallel/scatter.py): "psum"
    # all-reduces full [F, B, 3] histograms every pass (the A/B
    # oracle); "scatter" reduce-scatters them over a static feature
    # partition — each shard aggregates + split-searches only its 1/W
    # feature slice and per-shard winners sync as ONE SplitInfo record
    # each (ref: data_parallel_tree_learner.cpp:287-297), cutting
    # collective bytes/iter ~W-fold with bit-identical models. "auto"
    # picks scatter on multi-device meshes when the feature count
    # partitions evenly (voting learner: always — it pads internally),
    # psum otherwise. EFB-bundled / COO-sparse / streamed storage and
    # single-device runs always use psum.
    tpu_hist_reduce: str = "auto"
    # sparse row-wise COO histograms for ultra-sparse non-bundleable
    # input (ref: multi_val_sparse_bin.hpp:21): "auto" picks COO when
    # the estimated O(nnz) segment-sum work beats the dense/EFB layout,
    # "force"/"off" override. Serial tree learner only.
    tpu_sparse_hist: str = "auto"
    # bit-packed bin storage (ops/bin_pack.py): "auto" packs the device
    # bin tensor to 4-bit nibbles when max_bin <= 15 (2-bit pairs when
    # <= 3), halving/quartering the dominant per-pass bin read of the
    # cost model; "off" keeps the uint8 layout (the parity oracle —
    # packed histogram + partition outputs are bit-identical to it on
    # integer-valued gradients, tests/test_bin_pack.py). Dense unbundled
    # serial storage only; EFB/COO/mesh layouts stay unpacked.
    tpu_bin_pack: str = "auto"
    # out-of-core streaming training (ROADMAP item 1; io/streaming.py +
    # learner.StreamTreeGrower): keep the [F, N] bin tensor HOST-
    # resident, cut into section-aligned row slabs that stream to the
    # device wave-by-wave, double-buffered so slab k+1 uploads while
    # the fused histogram/partition programs consume slab k. "auto"
    # streams only when lgb.preflight()'s analytic memory model says
    # resident training does NOT fit device capacity (never on CPU
    # where capacity is unknown, unless LGBM_TPU_HBM_BYTES is set);
    # "on" forces streaming (raises when the shape is ineligible:
    # EFB/COO storage, forced splits, exact-order growth, interaction
    # or pairwise-monotone constraints, linear trees); "off" never
    # streams. Single-slab streamed models are bit-identical to
    # resident ones; quantized (int8-histogram) streaming is
    # bit-identical at ANY slab count (integer partial sums); plain
    # f32 multi-slab accumulation carries ~1-ulp-per-slab float-add
    # association drift.
    tpu_stream: str = "auto"
    # streaming slab size in rows; 0 = auto (the largest
    # section-aligned slab whose double-buffered working set fits the
    # capacity left after the resident row state — obs/memory.
    # stream_auto_slab_rows). Rounded up to the slab alignment
    # (pack-factor x 2048 rows) so every full slab shares one compiled
    # program shape.
    tpu_stream_slab_rows: int = 0
    # fuse the gradient/bagging element-wise pass into the histogram
    # waves: the objective's pointwise gradient (objectives.
    # pointwise_grad_fn — binary, L2 regression) is evaluated inside the
    # waved grower — and, on the pallas path, inside the multi-leaf
    # KERNEL itself, so the [N, 3] ghT operand never round-trips through
    # HBM (~0.5 GB/iter at Higgs shape). "auto" = on whenever the
    # objective supports it on the waved single-output fast path (no
    # GOSS, no quantized gradients); "on" forces it (XLA path included —
    # bitwise-identical gradients by construction); "off" disables.
    # The in-kernel histogram accumulation order matches the unfused
    # kernel exactly; only derived root-sum reductions are subject to
    # normal f32 reduction-order tolerance.
    tpu_fused_grad: str = "auto"
    # sibling histograms by subtraction (build the smaller child, derive
    # the larger from the pooled parent — serial_tree_learner.cpp:582),
    # with the wave schedule packing ONE slot per split. False = the
    # no-subtraction oracle: both children built directly, two slots
    # per split, ~17 instead of ~13 full-data passes at 255 leaves.
    # Documented tolerance: subtraction reorders f32 accumulation
    # (parent - small vs direct build), so the two modes agree to
    # normal cancellation tolerance, not bitwise. The obs
    # `hist_traffic` counters report both cost models.
    tpu_wave_subtract: bool = True
    # opt-in deterministic histogram accumulation (ROADMAP item 4's
    # numeric-parity debt): forces the XLA histogram path with
    # fixed-size chunking and Kahan-compensated cross-chunk sums, so
    # results are insensitive (to ~1 ulp) to chunking and to how
    # sharding regroups rows. Costs the pallas kernel's bandwidth
    # advantage — a parity/debug mode, not the perf path.
    deterministic_hist: bool = False
    # DART fused-path budget: the per-tree leaf-assignment history
    # ([T, K, N] device buffer that lets dropped-tree contributions be
    # recomputed without host round-trips) is only kept below this many
    # bytes; above it DART falls back to the host loop.
    tpu_dart_fused_max_bytes: int = 2 << 30
    # serving: rows per device dispatch of the streaming prediction
    # engine (ops/predict.py predict_raw_cached). Chunks are
    # shape-bucketed — full chunks run at exactly this size, the uneven
    # tail pads up to a power-of-two bucket — so any N reuses a small
    # fixed set of compiled traversal programs.
    tpu_predict_chunk: int = 1 << 20
    # TreeSHAP routing for predict(pred_contrib=True): "auto"/"on" run
    # the batched path-decomposed device kernel (ops/shap.py) — linear-
    # tree models always take the host path, which raises the
    # reference's linear-tree restriction — "off" forces the host
    # recursion (the parity oracle). Row chunks reuse
    # tpu_predict_chunk, internally capped (the per-row working set
    # scales with paths x depth, so SHAP streams smaller blocks).
    tpu_shap: str = "auto"
    # HBM capacity preflight (obs/memory.py): the analytic peak-memory
    # model is compared against device capacity at booster construction;
    # "warn" logs the verdict plus concrete knob recommendations when it
    # doesn't fit, "error" raises PreflightError (fail fast instead of
    # OOMing mid-run), "off" publishes the model through obs meta but
    # never judges. No effect on backends that report no memory stats
    # (CPU) unless LGBM_TPU_HBM_BYTES overrides the capacity.
    tpu_preflight: str = "warn"
    # training-health sentinels (obs/health.py): per-iteration NaN/Inf
    # sentinel counts folded into the fused training programs, plus
    # cross-shard drift digests of replicated state on multi-device
    # meshes. "off" (default) = guard-check-only no-op; "warn" records
    # the finding (obs counters + a log warning) and keeps training;
    # "error" raises the structured alarm (NonFiniteError / DriftError)
    # at the iteration that produced it — a diverged or NaN-poisoned
    # model fails fast instead of surfacing as a bad eval many
    # iterations later. Trained model bytes are bit-identical on vs off
    # (the sentinel adds pure reductions as extra program outputs).
    tpu_health: str = "off"
    # check period of the tpu_health sentinels (and of the telemetry
    # straggler probe): every N iterations. 1 = every iteration; larger
    # values amortize the tiny host sync the sentinel read costs.
    tpu_health_every: int = 1
    # device-time profiling window (obs/profile.py). "off" (default) =
    # one attribute check per program dispatch. "window" opens a
    # capture window at iteration 1 (after the compile-heavy first
    # iteration) spanning tpu_profile_window iterations: with
    # LGBM_TPU_PROFILE_DIR set the real jax.profiler trace is captured
    # and parsed into per-program device-busy seconds; without it the
    # profiler-free fallback re-times every instrumented dispatch with
    # a block_until_ready sync plus AOT micro-reruns at window close —
    # the same attribution pipeline, usable on CPU CI. "bench" keeps
    # the window open for the whole run (bench.py arms this itself
    # around its measured loop). Capture only adds syncs — trained
    # model bytes are bit-identical profiling on vs off. Results:
    # obs.profile.global_profile.summary()/roofline(), the
    # lgbmtpu_profile_* OpenMetrics families, bench JSON
    # device_seconds_by_tag/roofline, and a device lane in the Chrome
    # trace export.
    tpu_profile: str = "off"
    tpu_profile_window: int = 5
    # persistent XLA compile cache (compile_cache.py; ROADMAP item 2 —
    # kill cold start). "auto" (default) arms
    # jax.config.jax_compilation_cache_dir at the train/serve entry
    # UNLESS something already configured one (an existing jax.config
    # setting or JAX_COMPILATION_CACHE_DIR env wins); "on" forces it to
    # tpu_compile_cache_dir (falling back to LGBM_TPU_COMPILE_CACHE_DIR
    # env, then the repo-local .jax_cache); "off" opts this entry point
    # out without disarming anything. A cache-warm second process pays
    # ~zero compile seconds for the same programs (bench.py --coldstart
    # measures it; perf-gate check 10 caps it). Donation caveat: with
    # the cache armed on jaxlib<=0.4.36, buffer donation is dropped at
    # every program boundary (compile_cache.donation_allowed) — donating
    # into a cache-deserialized executable segfaults there; set "off"
    # to keep donation (peak-HBM) instead on those jaxlibs. Framework-
    # owned cache dirs are LRU-pruned once per process to
    # LGBM_TPU_COMPILE_CACHE_MAX_BYTES (default 4 GiB).
    tpu_compile_cache: str = "auto"
    tpu_compile_cache_dir: str = ""
    # fault-tolerant training (resilience/checkpoint.py). With
    # tpu_checkpoint_path set, engine.train snapshots FULL boosting
    # state (trees + scores + sampling masks + RNG streams + DART drop
    # bookkeeping + best-iteration) atomically every
    # tpu_checkpoint_every iterations, installs a SIGTERM handler that
    # finishes the in-flight iteration, snapshots, and exits with code
    # 75 (EXIT_PREEMPTED), and RESUMES from an existing checkpoint at
    # the same path — train-N-straight == train-k/kill/resume/train-
    # (N-k) bit-identically (tests/test_resilience.py). Checkpoints
    # carry a SHA-256 digest footer; a corrupt/truncated file raises
    # CorruptCheckpointError instead of resuming on torn state.
    # tpu_checkpoint_every=0 still snapshots on SIGTERM, just never
    # periodically.
    tpu_checkpoint_every: int = 0
    tpu_checkpoint_path: str = ""
    # elastic resume (resilience/elastic.py): a checkpoint whose
    # fingerprint differs from the rebuilt run in MESH SHAPE ONLY
    # (tpu_num_shards drift — W-shard snapshot restored on a W'-shard
    # mesh) is re-sharded through the rebuilt booster's sharding and
    # admitted after a cross-shard drift-digest gate on the restored
    # state (ElasticResumeError names any diverged shard before it
    # votes). false = any fingerprint drift, mesh included, refuses
    # with ResumeMismatchError. Structural drift (objective, dataset
    # shape, tree counts) ALWAYS refuses.
    tpu_elastic_resume: bool = True
    # distributed-training watchdog (resilience/watchdog.py). With
    # tpu_watchdog_deadline_s > 0, engine.train runs a per-iteration
    # heartbeat allgather (reusing the obs/health straggler machinery)
    # bounded by this deadline: a peer that hangs mid-collective turns
    # the infinite stall into a structured PeerLostError within the
    # deadline, the flight recorder dumps a postmortem, a checkpoint is
    # written (tpu_checkpoint_path set), and the process exits with
    # code 75 (EXIT_PREEMPTED) so a supervisor restarts the survivors
    # on a shrunk mesh through the elastic-resume path. 0 = watchdog
    # off (single-host default — collectives can't be peer-hung).
    tpu_watchdog_deadline_s: float = 0.0
    # continual training (resilience/continual.py; lgb.continual_train).
    # Each ingested chunk trains one GENERATION of tpu_continual_rounds
    # extra iterations onto the long-lived model ("extend" mode;
    # "refit" refreshes leaf values on the fresh chunk instead, decay
    # refit_decay_rate). A held-out tpu_continual_eval_fraction slice
    # of every chunk feeds the obs/health eval NaN/spike/plateau
    # anomaly detector — the automatic accept-vs-rollback trigger; a
    # rejected generation restores the last-good snapshot (bounded at
    # tpu_continual_retain retained generations). Accepted generations
    # hot-swap into the serve registry through the transactional
    # validate-predict path with a bit-identical-on-reload assertion,
    # so a rolled-back generation is never observable from the serve
    # side. Exported as lgbmtpu_continual_* (obs/export.py).
    tpu_continual_rounds: int = 10
    tpu_continual_retain: int = 3
    tpu_continual_eval_fraction: float = 0.2
    tpu_continual_mode: str = "extend"
    # serving (serve/ async model server; task=serve and the in-process
    # API). Micro-batching: requests coalesce until serve_max_batch_rows
    # rows are pending or the OLDEST pending request has waited
    # serve_max_wait_ms; requests of <= serve_lowlat_max_rows rows skip
    # the batcher entirely and dispatch through the AOT-compiled
    # low-latency path. serve_cache_bytes bounds the total packed-
    # ensemble bytes the multi-tenant registry keeps resident (LRU pack
    # eviction; 0 = unbounded). serve_request_rows is the CLI replay's
    # rows-per-request (0 = a mixed small/large size cycle).
    # serve_metrics_port exposes /metrics + /healthz + /readyz on
    # task=serve (obs/export.py): -1 = off, 0 = ephemeral port (logged
    # in the stats line), >0 = that port.
    serve_max_batch_rows: int = 8192
    serve_max_wait_ms: float = 2.0
    serve_lowlat_max_rows: int = 64
    serve_cache_bytes: int = 1 << 30
    serve_request_rows: int = 0
    serve_metrics_port: int = -1
    # serving graceful degradation (resilience/degrade.py). Per-request
    # deadline: a request older than serve_deadline_ms fails fast with
    # a structured DeadlineExceeded instead of occupying the batcher
    # (0 = no deadline). Bounded admission: when more than
    # serve_max_queue_rows rows are already queued/in flight, new
    # arrivals are shed with ServerOverloaded carrying retry-after
    # semantics (0 = unbounded). Transient registry pack/compile
    # failures retry with exponential backoff (serve_retry_max
    # attempts, base serve_retry_backoff_ms). A model whose dispatches
    # keep faulting trips a per-model circuit breaker after
    # serve_breaker_threshold consecutive failures (0 = breaker off);
    # the breaker fails fast for serve_breaker_reset_s seconds, then
    # half-opens one probe. All events are counted in obs.metrics and
    # exported as lgbmtpu_resilience_* OpenMetrics families.
    serve_deadline_ms: float = 0.0
    serve_max_queue_rows: int = 0
    serve_retry_max: int = 2
    serve_retry_backoff_ms: float = 10.0
    serve_breaker_threshold: int = 5
    serve_breaker_reset_s: float = 30.0
    # serialized AOT serving artifacts (serve/artifacts.py): when set,
    # every low-latency executable a model compiles is exported to this
    # directory (jax.experimental.serialize_executable), keyed by an
    # artifact fingerprint (format version + jax/jaxlib + backend +
    # packed-ensemble digest + bucket/width), and ModelServer.warm() /
    # LRU re-admission re-import instead of recompiling — a replica
    # restart warms from disk in milliseconds with ZERO
    # serve/lowlat compiles (obs-counter-asserted by
    # tools/check_coldstart.py). Any fingerprint mismatch falls back to
    # a fresh compile with bit-identical predictions either way.
    # Empty = off.
    serve_artifact_dir: str = ""
    # serving fleet (serve/fleet.py FleetRouter): N ModelServer
    # replicas behind health-gated routing. serve_fleet_replicas sizes
    # the fleet (task-level drivers and bench.py --fleet build this
    # many in-process replicas; tools/check_fleet.py spawns them as
    # subprocesses). serve_probe_interval_ms paces the /readyz +
    # /healthz probe loop that drives the quarantine/reinstate state
    # machine. serve_hedge_ms > 0 arms hedged dispatch: a request
    # still unanswered after this many ms fires a duplicate on another
    # healthy replica and the first answer wins (bit-identical by the
    # pack contract, asserted) — a p99 tail cutter that costs duplicate
    # work, so off (0) by default.
    serve_fleet_replicas: int = 3
    serve_probe_interval_ms: float = 50.0
    serve_hedge_ms: float = 0.0

    # stash for unknown params (kept for forward-compat, like reference ignores)
    extra_params: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------------
    @staticmethod
    def canonical_key(key: str) -> str:
        return _ALIAS_TO_CANONICAL.get(key, key)

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        cfg = cls()
        cfg.update(params or {})
        return cfg

    def update(self, params: Dict[str, Any]) -> None:
        field_types = {f.name: f.type for f in dataclasses.fields(self)}
        canon_params: Dict[str, Any] = {}
        for key, value in params.items():
            canon = self.canonical_key(str(key))
            # first alias wins for conflicting duplicates, matching reference
            # KeyAliasTransform behavior of preferring the canonical key
            if canon in canon_params and key != canon:
                continue
            canon_params[canon] = value
        for key, value in canon_params.items():
            if key in field_types and key != "extra_params":
                setattr(self, key, _coerce(value, getattr(self, key)))
            else:
                self.extra_params[key] = value
        if not hasattr(self, "explicit_keys"):
            self.explicit_keys = set()
        new_keys = set(canon_params) - self.explicit_keys
        self.explicit_keys.update(canon_params)
        self._post_process()
        # warn only for keys newly set by THIS update: Booster and
        # Dataset each build a Config from overlapping param dicts and
        # the warning should fire once per distinct user setting
        self._warn_unsupported(new_keys)

    # params that are accepted (for config compatibility) but have no
    # effect in this build; explicitly setting one warns instead of
    # silently no-oping. Audited by tests/test_param_honesty.py.
    _UNSUPPORTED_EXPLICIT = {
        "two_round": "two-round loading is not needed (single in-memory "
                     "binning pass)",
        "pre_partition": "pre-partitioned loading is not implemented",
        "gpu_platform_id": "OpenCL params are ignored on TPU",
        "gpu_device_id": "OpenCL params are ignored on TPU",
        "gpu_use_dp": "OpenCL params are ignored on TPU",
        "num_gpu": "multi-device training uses the TPU mesh "
                   "(tpu_num_shards), not num_gpu",
    }

    def _warn_unsupported(self, new_keys) -> None:
        from . import log
        # self.verbosity is already set by this update(); honor it even
        # before the Booster installs the global log level (verbosity=-1
        # in the same params dict must silence these, like the reference)
        if self.verbosity < 0:
            return
        for key, msg in self._UNSUPPORTED_EXPLICIT.items():
            if key in new_keys and key not in _WARNED_UNSUPPORTED:
                _WARNED_UNSUPPORTED.add(key)
                log.warning(f"{key} has no effect: {msg}")

    def _post_process(self) -> None:
        self.objective = _OBJECTIVE_ALIASES.get(str(self.objective).lower(), self.objective)
        boosting = _BOOSTING_ALIASES.get(str(self.boosting).lower(), self.boosting)
        if boosting == "goss":
            boosting = "gbdt"
            self.data_sample_strategy = "goss"
        self.boosting = boosting
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            raise ValueError("num_class must be >1 for multiclass objectives")
        if self.metric is None:
            metrics = []
        elif isinstance(self.metric, str):
            metrics = [m for m in self.metric.split(",") if m]
        else:
            metrics = list(self.metric)
        self.metric = [_METRIC_ALIASES.get(str(m).lower(), str(m)) for m in metrics]
        if self.eval_at is None:
            self.eval_at = [1, 2, 3, 4, 5]
        elif isinstance(self.eval_at, str):
            self.eval_at = [int(x) for x in self.eval_at.split(",") if x]
        else:
            self.eval_at = [int(x) for x in self.eval_at]
        if self.valid is None:
            self.valid = []
        elif isinstance(self.valid, str):
            self.valid = [v for v in self.valid.split(",") if v]

    def default_metric(self) -> List[str]:
        """Metric implied by the objective when none requested (ref: config.cpp)."""
        obj_to_metric = {
            "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
            "poisson": "poisson", "quantile": "quantile", "mape": "mape",
            "gamma": "gamma", "tweedie": "tweedie",
            "binary": "binary_logloss",
            "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
            "cross_entropy": "cross_entropy", "cross_entropy_lambda": "cross_entropy_lambda",
            "lambdarank": "ndcg", "rank_xendcg": "ndcg",
        }
        m = obj_to_metric.get(self.objective)
        return [m] if m else []

    def to_params(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out.pop("extra_params", None)
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def kv2map(args: List[str]) -> Dict[str, str]:
        """Parse `key=value` CLI tokens (ref: Config::KV2Map config.h:101)."""
        out: Dict[str, str] = {}
        for arg in args:
            arg = arg.strip()
            if not arg or arg.startswith("#"):
                continue
            if "=" not in arg:
                continue
            key, value = arg.split("=", 1)
            key = key.strip()
            value = value.split("#", 1)[0].strip()
            if key:
                out[key] = value
        return out


def _coerce(value: Any, default: Any) -> Any:
    if default is None or value is None:
        return value
    if isinstance(default, bool):
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "+", "on")
        return bool(value)
    if isinstance(default, int) and not isinstance(default, bool):
        return int(float(value)) if not isinstance(value, int) else value
    if isinstance(default, float):
        return float(value)
    if isinstance(default, str):
        return str(value)
    if isinstance(default, list) and isinstance(value, str):
        return [v for v in value.split(",") if v]
    return value
