"""Training callbacks (ref: python-package/lightgbm/callback.py:93-281)."""

from __future__ import annotations

import collections
from . import log
from typing import Callable, Dict, List

# `telemetry` (defaulted, so positional construction stays compatible)
# carries the obs.metrics per-iteration dict when telemetry is enabled,
# the way evaluation_result_list carries metric evals
CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list", "telemetry"],
    defaults=[None])


class EarlyStopException(Exception):
    """(ref: callback.py EarlyStopException)"""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """(ref: callback.py:93 _LogEvaluationCallback)"""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list)
            # user attached this callback explicitly: print regardless of
            # the global verbosity gate (reference callbacks do the same)
            log.info(f"[{env.iteration + 1}]\t{result}", force=True)
    _callback.order = 10
    # per-iteration evals must run for this callback to have anything
    # to print, even when metric_freq suppresses them
    _callback.needs_eval = True
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    """(ref: callback.py:140 _RecordEvaluationCallback)"""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for name, metric, _, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict()) \
                .setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict()) \
                .setdefault(metric, []).append(value)
    _callback.order = 20
    _callback.needs_eval = True
    return _callback


def _fmt_telemetry(t: Dict) -> str:
    """One compact line per iteration: headline numbers then phase times."""
    parts = []
    if "iteration_seconds" in t:
        parts.append(f"iter={t['iteration_seconds']:.3f}s")
    for key in ("leaves_grown", "best_gain", "grad_norm", "hess_norm",
                "grad_clipped", "jit_recompiles"):
        if key in t:
            v = t[key]
            parts.append(f"{key}={v:.4g}" if isinstance(v, float)
                         else f"{key}={v}")
    phases = t.get("phases") or {}
    for name in sorted(phases, key=phases.get, reverse=True)[:4]:
        parts.append(f"{name}={phases[name]:.3f}s")
    return " ".join(parts)


def log_telemetry(period: int = 1) -> Callable:
    """Print the obs.metrics per-iteration summary every `period`
    iterations (the telemetry analog of log_evaluation; enables the
    metrics registry for the run when attached)."""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.telemetry is not None and \
                (env.iteration + 1) % period == 0:
            log.info(f"[{env.iteration + 1}]\t"
                     f"{_fmt_telemetry(env.telemetry)}", force=True)
    _callback.order = 15
    _callback.needs_telemetry = True
    return _callback


def record_telemetry(result: Dict) -> Callable:
    """Append each iteration's telemetry dict into `result` as lists
    keyed by metric name (the telemetry analog of record_evaluation;
    enables the metrics registry for the run when attached).

    Lists stay iteration-aligned: a metric absent on some iteration
    (e.g. jit_recompiles only appears on compiling iterations) records
    None there, so ``result[k][i]`` is always iteration i."""
    if not isinstance(result, dict):
        raise TypeError("result must be a dict")
    n_seen = [0]

    def _callback(env: CallbackEnv) -> None:
        t = env.telemetry
        if t is None:
            return
        flat = {}
        for key, value in t.items():
            if key == "phases":
                for pname, secs in value.items():
                    flat[f"phase/{pname}"] = secs
            else:
                flat[key] = value
        for key, value in flat.items():
            # back-fill iterations recorded before this key first appeared
            result.setdefault(key, [None] * n_seen[0]).append(value)
        n_seen[0] += 1
        for lst in result.values():
            if len(lst) < n_seen[0]:  # key missing this iteration
                lst.append(None)
    _callback.order = 25
    _callback.needs_telemetry = True
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """(ref: callback.py:185 _ResetParameterCallback)"""
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} must match num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta=0.0) -> Callable:
    """(ref: callback.py:224 _EarlyStoppingCallback)"""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _is_train(name: str, env) -> bool:
        return name == "training"

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not env.params.get("boosting", "gbdt") == "rf"
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one validation set is required")
        if verbose:
            log.info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds", force=True)
        n = len(env.evaluation_result_list)
        deltas = (min_delta if isinstance(min_delta, list)
                  else [min_delta] * n)
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for (name, metric, _, higher_better), delta in zip(
                env.evaluation_result_list, deltas):
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y, d=delta: x > y + d)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y, d=delta: x < y - d)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _) in \
                enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if _is_train(name, env):
                continue
            if first_metric_only and first_metric[0] != metric.split(" ")[-1]:
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info(f"Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]", force=True)
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    log.info(f"Did not meet early stopping. Best iteration "
                             f"is:\n[{best_iter[i] + 1}]", force=True)
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    # engine.train forces per-iteration evals when this callback is
    # present (the reference's early stopping ignores metric_freq too)
    _callback.needs_eval = True
    return _callback
