"""Arrow C-ABI ingestion + streaming push tests (ref: test_arrow.py,
test_stream.cpp:253 — here with hand-built C-ABI structs since pyarrow
is not in the image; the PyCapsule protocol is exercised for real)."""

import ctypes

import numpy as np
import pytest

from conftest import make_binary

import lightgbm_tpu as lgb
from lightgbm_tpu.io.arrow_ingest import (ArrowArray, ArrowSchema,
                                          arrow_to_matrix, arrow_to_vector)
from lightgbm_tpu.io.streaming import DatasetBuilder

PyCapsule_New = ctypes.pythonapi.PyCapsule_New
PyCapsule_New.restype = ctypes.py_object
PyCapsule_New.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]


class _FakeArrowTable:
    """Minimal __arrow_c_array__ exporter: a struct array whose children
    are float64/int32 numpy columns (zero-copy buffers kept alive on
    self)."""

    def __init__(self, columns, names, validity=None):
        self._keep = []
        self._names = [n.encode() for n in names]
        n = len(columns[0])

        child_schemas = []
        child_arrays = []
        fmt_for = {np.dtype(np.float64): b"g", np.dtype(np.float32): b"f",
                   np.dtype(np.int32): b"i", np.dtype(np.int64): b"l"}
        for j, col in enumerate(columns):
            col = np.ascontiguousarray(col)
            self._keep.append(col)
            cs = ArrowSchema()
            cs.format = fmt_for[col.dtype]
            cs.name = self._names[j]
            cs.metadata = None
            cs.flags = 0
            cs.n_children = 0
            cs.children = None
            cs.dictionary = None
            cs.release = None
            child_schemas.append(cs)

            ca = ArrowArray()
            ca.length = n
            ca.offset = 0
            ca.n_children = 0
            ca.children = None
            ca.dictionary = None
            ca.release = 1  # non-null: "owned elsewhere"
            bufs = (ctypes.c_void_p * 2)()
            vmask = None if validity is None else validity[j]
            if vmask is None:
                ca.null_count = 0
                bufs[0] = None
            else:
                ca.null_count = int((~vmask).sum())
                packed = np.packbits(vmask.astype(np.uint8),
                                     bitorder="little")
                self._keep.append(packed)
                bufs[0] = packed.ctypes.data
            bufs[1] = col.ctypes.data
            self._keep.append(bufs)
            ca.n_buffers = 2
            ca.buffers = bufs
            child_arrays.append(ca)

        self._child_schemas = child_schemas
        self._child_arrays = child_arrays
        cs_ptrs = (ctypes.POINTER(ArrowSchema) * len(columns))(
            *[ctypes.pointer(s) for s in child_schemas])
        ca_ptrs = (ctypes.POINTER(ArrowArray) * len(columns))(
            *[ctypes.pointer(a) for a in child_arrays])
        self._keep += [cs_ptrs, ca_ptrs]

        self._schema = ArrowSchema()
        self._schema.format = b"+s"
        self._schema.name = b""
        self._schema.metadata = None
        self._schema.flags = 0
        self._schema.n_children = len(columns)
        self._schema.children = cs_ptrs
        self._schema.dictionary = None
        self._schema.release = None

        self._array = ArrowArray()
        self._array.length = n
        self._array.null_count = 0
        self._array.offset = 0
        self._array.n_buffers = 1
        bufs0 = (ctypes.c_void_p * 1)()
        bufs0[0] = None
        self._keep.append(bufs0)
        self._array.buffers = bufs0
        self._array.n_children = len(columns)
        self._array.children = ca_ptrs
        self._array.dictionary = None
        self._array.release = 1

    def __arrow_c_array__(self, requested_schema=None):
        return (PyCapsule_New(ctypes.byref(self._schema), b"arrow_schema",
                              None),
                PyCapsule_New(ctypes.byref(self._array), b"arrow_array",
                              None))


class _FakeArrowVector(_FakeArrowTable):
    def __init__(self, values):
        super().__init__([np.ascontiguousarray(values)], ["v"])

    def __arrow_c_array__(self, requested_schema=None):
        return (PyCapsule_New(ctypes.byref(self._child_schemas[0]),
                              b"arrow_schema", None),
                PyCapsule_New(ctypes.byref(self._child_arrays[0]),
                              b"arrow_array", None))


def test_arrow_table_to_matrix():
    cols = [np.arange(5, dtype=np.float64),
            np.array([1, 2, 3, 4, 5], np.int32)]
    table = _FakeArrowTable(cols, ["a", "b"])
    mat, names = arrow_to_matrix(table)
    assert names == ["a", "b"]
    np.testing.assert_array_equal(mat[:, 0], cols[0])
    np.testing.assert_array_equal(mat[:, 1], cols[1].astype(np.float64))


def test_arrow_nulls_become_nan():
    col = np.array([1.0, 2.0, 3.0, 4.0])
    valid = np.array([True, False, True, True])
    table = _FakeArrowTable([col], ["x"], validity=[valid])
    mat, _ = arrow_to_matrix(table)
    assert np.isnan(mat[1, 0])
    assert mat[0, 0] == 1.0 and mat[2, 0] == 3.0


def test_arrow_dataset_trains():
    X, y = make_binary(400, 4)
    table = _FakeArrowTable([np.ascontiguousarray(X[:, j]) for j in range(4)],
                            [f"f{j}" for j in range(4)])
    label = _FakeArrowVector(y.astype(np.float64))
    ds = lgb.Dataset(table, label=label, params={"verbosity": -1})
    ds.construct()
    assert ds._binned.feature_names[:2] == ["f0", "f1"]
    bst = lgb.train({"objective": "binary", "verbosity": -1}, ds,
                    num_boost_round=5)
    assert bst.num_trees() == 5


def test_arrow_vector():
    v = arrow_to_vector(_FakeArrowVector(np.array([3.0, 1.0, 2.0])))
    np.testing.assert_array_equal(v, [3.0, 1.0, 2.0])


# ----------------------------------------------------------------------
@pytest.mark.slow
def test_streaming_builder_matches_monolithic():
    X, y = make_binary(600, 5)
    w = np.abs(np.random.RandomState(0).randn(600)) + 0.5

    b = DatasetBuilder(num_features=5, params={"verbosity": -1})
    for s in range(0, 600, 150):
        b.push_rows(X[s:s + 150], label=y[s:s + 150], weight=w[s:s + 150])
    assert b.num_pushed == 600
    ds_stream = b.finalize()

    ds_mono = lgb.Dataset(X, label=y, weight=w, params={"verbosity": -1})
    ds_mono.construct()
    np.testing.assert_array_equal(ds_stream._binned.bins_fm,
                                  ds_mono._binned.bins_fm)

    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
    p1 = lgb.train(dict(params), ds_stream, num_boost_round=5).predict(X)
    p2 = lgb.train(dict(params), ds_mono, num_boost_round=5).predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_streaming_builder_validation():
    b = DatasetBuilder(num_features=3)
    b.push_rows(np.zeros((4, 3)), label=np.zeros(4))
    with pytest.raises(ValueError):
        b.push_rows(np.zeros((4, 2)), label=np.zeros(4))  # wrong F
    with pytest.raises(ValueError):
        b.push_rows(np.zeros((4, 3)))  # label missing after being given
    b.push_rows(np.zeros((2, 3)), label=np.ones(2))
    ds = b.finalize()
    assert ds._binned.num_data == 6
    with pytest.raises(RuntimeError):
        b.finalize()



def test_sequence_interface_matches_array():
    """lightgbm.Sequence analog (ref: basic.py:841): batched read-through
    must produce the identical model to direct array input, including a
    LIST of sequences (row-concatenated chunks)."""
    import lightgbm_tpu as lgb

    class ArrSeq(lgb.Sequence):
        batch_size = 128

        def __init__(self, a):
            self.a = a

        def __getitem__(self, idx):
            return self.a[idx]

        def __len__(self):
            return len(self.a)

    rng = np.random.RandomState(0)
    X = rng.randn(700, 5)
    y = (X[:, 0] > 0).astype(np.float32)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    b_arr = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=4)
    b_seq = lgb.train(dict(p), lgb.Dataset(ArrSeq(X), label=y),
                      num_boost_round=4)
    b_lst = lgb.train(dict(p),
                      lgb.Dataset([ArrSeq(X[:300]), ArrSeq(X[300:])],
                                  label=y), num_boost_round=4)
    np.testing.assert_allclose(b_seq.predict(X), b_arr.predict(X))
    np.testing.assert_allclose(b_lst.predict(X), b_arr.predict(X))
