"""Deadline-bounded micro-batching of concurrent predict requests.

Many small concurrent requests are the wrong shape for the inference
engine; one medium batch is the right one. The batcher coalesces: a
request appends its rows to the pending queue and awaits a future; the
queue flushes when either

- the pending rows reach ``max_batch_rows`` (size trigger), or
- the OLDEST pending request has waited ``max_wait_s`` (deadline
  trigger — the timer starts when the queue becomes non-empty and is
  never extended by later arrivals, so no request waits more than
  ``max_wait_s`` before its batch is dispatched).

A flush concatenates the pending rows, runs ``predict_fn`` on the
executor (so the event loop keeps accepting requests while the device
works — that in-flight window is exactly where the next batch
coalesces), and scatters row slices back to the per-request futures.

Bit-parity: tree traversal is independent per row and the per-row f32
class-sum order does not depend on batch size, so a coalesced request's
slice is bit-identical to calling ``predict_fn`` on its rows alone
(asserted by tests/test_serve.py). A single oversized request (more
rows than ``max_batch_rows``) dispatches immediately as its own batch —
the engine's chunking handles arbitrarily large row counts.

Single-loop use only: all bookkeeping runs on the event-loop thread, so
no locks are needed.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import Awaitable, Callable, List, Tuple

import numpy as np

from ..obs.metrics import global_metrics
from ..obs.trace import global_tracer

# process-wide batch ids: the link key between a coalesced batch's
# device span and the request spans it carried (request tracing)
_batch_ids = itertools.count(1)


class MicroBatcher:
    def __init__(self, predict_fn: Callable[[np.ndarray], np.ndarray],
                 max_batch_rows: int = 8192, max_wait_s: float = 0.002,
                 executor=None, counter_prefix: str = "serve"):
        self._predict_fn = predict_fn
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.max_wait_s = max(float(max_wait_s), 0.0)
        self._executor = executor
        # counter family: "serve" for raw-score batches, "explain" for
        # the SHAP-contribution batchers (server.explain) — the flush
        # bookkeeping below is otherwise identical
        self.counter_prefix = str(counter_prefix)
        # (x, future, trace, deadline, arrival_t0) per pending request
        self._pending: List[Tuple[np.ndarray, asyncio.Future, object,
                                  float, float]] = []
        self._pending_rows = 0
        self._timer = None
        self._oldest_t0 = 0.0

    @property
    def pending_rows(self) -> int:
        return self._pending_rows

    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray, trace=None,
               deadline: float = 0.0) -> Awaitable[np.ndarray]:
        """Queue `x` ([B, F]) for the next coalesced dispatch; resolves
        to the raw [B, K] scores for exactly these rows. Must be called
        on the event-loop thread. `trace` (a server ``_RequestTrace``,
        present only while the tracer runs) receives this request's
        queue-wait/device-time attribution and batch link at flush.
        `deadline` (a ``time.perf_counter()`` timestamp, 0 = none): a
        request still pending past its deadline is failed with
        ``DeadlineExceeded`` at flush time and never rides a batch —
        an expired waiter must not cost device work."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if self._pending and \
                self._pending_rows + x.shape[0] > self.max_batch_rows:
            # dispatching this arrival with the queue would overshoot
            # the cap: flush first, so steady-state batches never
            # exceed max_batch_rows (and never outgrow the warmed
            # shape-bucket set — only a single oversized request can)
            self._flush(loop)
        if not self._pending:
            self._oldest_t0 = time.perf_counter()
        self._pending.append((x, fut, trace, deadline,
                              time.perf_counter()))
        self._pending_rows += x.shape[0]
        if self._pending_rows >= self.max_batch_rows:
            self._flush(loop)
        elif self._timer is None:
            self._timer = loop.call_later(self.max_wait_s,
                                          self._flush, loop)
        return fut

    def flush(self) -> None:
        """Force-dispatch whatever is pending (server shutdown path)."""
        if self._pending:
            self._flush(asyncio.get_running_loop())

    # ------------------------------------------------------------------
    def _flush(self, loop) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch = self._pending
        if not batch:
            return
        self._pending = []
        self._pending_rows = 0

        # deadline enforcement (resilience): waiters whose budget
        # expired while queued fail fast HERE and are excluded from the
        # dispatched batch — they must not occupy device time
        now = time.perf_counter()
        expired = [(x, fut, t0) for x, fut, _, dl, t0 in batch
                   if dl and now > dl]
        if expired:
            from ..resilience.errors import DeadlineExceeded
            for x, fut, t0 in expired:
                global_metrics.inc_counter("resilience/deadline_exceeded")
                if not fut.done():
                    fut.set_exception(DeadlineExceeded(
                        f"request ({x.shape[0]} rows) expired in the "
                        "batch queue", elapsed_s=now - t0))
            batch = [e for e in batch if not (e[3] and now > e[3])]
            if not batch:
                return

        xs = [x for x, _, _, _, _ in batch]
        xcat = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
        pre = self.counter_prefix
        global_metrics.inc_counter(f"{pre}/batches")
        global_metrics.inc_counter(f"{pre}/batched_rows", xcat.shape[0])
        if len(batch) > 1:
            global_metrics.inc_counter(f"{pre}/coalesced_requests",
                                       len(batch))
        global_metrics.note_latency(
            f"{pre}/batch_wait", time.perf_counter() - self._oldest_t0)

        traces = [tr for _, _, tr, _, _ in batch if tr is not None]
        if traces:
            # queue wait ends now; the device span is timed on the
            # executor thread and linked back by batch_id
            batch_id = next(_batch_ids)
            flush_ns = time.perf_counter_ns()
            for tr in traces:
                tr.queue_ns = flush_ns - tr.t0_ns
                tr.batch_id = batch_id
            rows = int(xcat.shape[0])
            predict_fn = self._predict_fn

            def timed_predict(xb=xcat):
                t_dev = time.perf_counter_ns()
                out = predict_fn(xb)
                dev_ns = time.perf_counter_ns() - t_dev
                for tr in traces:
                    tr.device_ns = dev_ns
                global_tracer.add_complete_span(
                    "serve/batch", t_dev, dev_ns,
                    args={"batch_id": batch_id, "rows": rows,
                          "trace_ids": [tr.trace_id for tr in traces]})
                return out

            task = loop.run_in_executor(self._executor, timed_predict)
        else:
            task = loop.run_in_executor(self._executor, self._predict_fn,
                                        xcat)

        def scatter(done: asyncio.Future) -> None:
            try:
                out = done.result()
            except BaseException as exc:  # propagate to every waiter
                for _, fut, _, _, _ in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                return
            lo = 0
            for x, fut, _, _, _ in batch:
                hi = lo + x.shape[0]
                if not fut.done():  # waiter may have been cancelled
                    fut.set_result(out[lo:hi])
                lo = hi

        task.add_done_callback(scatter)
