#!/usr/bin/env python
"""CI smoke for the training-health observability layer (obs/health.py).

Four steps, in order:

1. **Disabled path emits nothing** — before anything arms health, a
   plain training run must leave ``global_health.summary()`` empty and
   ``render_openmetrics()`` free of any ``lgbmtpu_health_*`` family.

2. **Health families present** — with telemetry + health armed, a
   mesh (data-parallel) training run plus a drift check, straggler
   probe and collective microprobe must surface the
   ``lgbmtpu_health_*`` families in the OpenMetrics document, and the
   whole document must stay valid Prometheus exposition line by line
   (reusing check_metrics_endpoint.validate_exposition).

3. **NaN sentinel fires on a poisoned-label fixture** — one NaN label
   in an L2 regression makes a NaN gradient; ``tpu_health=warn`` must
   record it within the first iteration, ``tpu_health=error`` must
   raise ``NonFiniteError``.

4. **Drift sentinel fires on injected divergence** — a replicated
   array rebuilt with one device's copy perturbed must be flagged by
   ``check_drift`` (warn records, error raises ``DriftError``).

Exit 0 = pass. Usage: python tools/check_health.py
Wired into the quick verification tier via tests/test_health.py.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

import numpy as np  # noqa: E402

REQUIRED_FAMILIES = (
    "lgbmtpu_health_collective_calls_total",
    "lgbmtpu_health_collective_bytes_total",
    "lgbmtpu_health_collective_seconds_total",
    "lgbmtpu_health_straggler_skew",
    "lgbmtpu_health_drift_checks_total",
    "lgbmtpu_health_drift_mismatch_total",
    "lgbmtpu_health_nonfinite_total",
)


def _fail(msg: str) -> int:
    print(f"CHECK-HEALTH FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.export import render_openmetrics
    from lightgbm_tpu.obs.health import (DriftError, NonFiniteError,
                                         global_health)
    from lightgbm_tpu.obs.metrics import global_metrics
    from lightgbm_tpu.obs.trace import global_tracer
    from check_metrics_endpoint import validate_exposition

    rng = np.random.RandomState(0)
    X = rng.randn(1024, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.3).astype(np.float32)

    # --- 1. disabled path emits nothing ------------------------------
    global_health.reset()
    lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7},
              lgb.Dataset(X, label=y), num_boost_round=2)
    if global_health.summary():
        return _fail(f"disabled run left a non-empty health summary: "
                     f"{global_health.summary()}")
    if "lgbmtpu_health_" in render_openmetrics():
        return _fail("disabled run leaked lgbmtpu_health_* families "
                     "into the OpenMetrics document")
    print("# disabled path emits nothing: OK")

    # --- 2. armed mesh run surfaces every family ---------------------
    global_metrics.enable()  # arms tracer/watermarks/xla/health
    try:
        bst = lgb.Booster(
            {"objective": "binary", "tree_learner": "voting", "top_k": 3,
             "tpu_num_shards": 8, "num_leaves": 7, "tpu_wave_max": 0,
             "tpu_health": "warn", "min_data_in_leaf": 5,
             "verbosity": -1}, lgb.Dataset(X, label=y))
        for _ in range(2):
            bst.update()
        mesh = bst._gbdt.mesh
        global_health.probe_collectives(mesh)
        global_health.straggler_probe()
        # drift families render once a check ran (mismatch stays 0 on
        # a clean replicated array)
        from jax.sharding import NamedSharding, PartitionSpec as P
        clean = jax.device_put(np.arange(16, dtype=np.float32),
                               NamedSharding(mesh, P()))
        global_health.check_drift(mesh, {"probe": clean}, mode="warn")
        # the nonfinite family renders once any count exists; seed the
        # zero-count kinds so the family is present on a healthy run
        global_health.nonfinite.setdefault("grad", 0)
        text = render_openmetrics()
    finally:
        global_metrics.disable()
        global_tracer.disable()
        global_health.disable()
        from lightgbm_tpu.obs.memory import global_watermarks
        from lightgbm_tpu.obs.xla import global_xla
        global_watermarks.disable()
        global_xla.disable()

    errors, families = validate_exposition(text)
    if errors:
        return _fail("invalid exposition with health families: "
                     + "; ".join(errors[:5]))
    missing = [f for f in REQUIRED_FAMILIES if f not in families]
    if missing:
        return _fail(f"health families missing from /metrics: {missing}")
    print(f"# health families present ({len(families)} total families, "
          f"exposition valid): OK")

    # --- 3. NaN sentinel on a poisoned-label fixture -----------------
    y_poison = X[:, 0].astype(np.float64).copy()
    y_poison[7] = np.nan
    global_health.reset()
    lgb.train({"objective": "regression", "verbosity": -1,
               "tpu_health": "warn", "num_leaves": 7},
              lgb.Dataset(X, label=y_poison), num_boost_round=1)
    if not global_health.nonfinite.get("grad"):
        return _fail("warn-mode NaN sentinel did not record poisoned "
                     f"gradients: {global_health.nonfinite}")
    if global_health.last_nonfinite is None or \
            global_health.last_nonfinite.get("iteration") != 0:
        return _fail("NaN sentinel did not fire within the first "
                     f"iteration: {global_health.last_nonfinite}")
    try:
        lgb.train({"objective": "regression", "verbosity": -1,
                   "tpu_health": "error", "num_leaves": 7},
                  lgb.Dataset(X, label=y_poison), num_boost_round=3)
        return _fail("error-mode NaN sentinel did not raise")
    except NonFiniteError:
        pass
    print("# NaN sentinel fires on poisoned labels (warn records, "
          "error raises): OK")

    # --- 4. drift sentinel on injected divergence --------------------
    from jax.sharding import NamedSharding, PartitionSpec as P
    from lightgbm_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.get_mesh(8)
    host = np.arange(64, dtype=np.float32)
    copies = []
    for i, dev in enumerate(mesh.devices.flat):
        h = host.copy()
        if i == 5:
            h[3] += 1.0  # the diverged replica
        copies.append(jax.device_put(h, dev))
    diverged = jax.make_array_from_single_device_arrays(
        host.shape, NamedSharding(mesh, P()), copies)
    global_health.reset()
    mm = global_health.check_drift(mesh, {"state": diverged}, mode="warn")
    if not mm or mm[0]["shards"] != [5]:
        return _fail(f"injected divergence not attributed to shard 5: "
                     f"{mm}")
    try:
        global_health.check_drift(mesh, {"state": diverged}, mode="error")
        return _fail("error-mode drift check did not raise DriftError")
    except DriftError:
        pass
    print("# drift sentinel flags injected divergence (shard 5): OK")

    print("check_health OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
