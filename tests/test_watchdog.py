"""Distributed-training watchdog (ISSUE 17, train half).

- ``Watchdog.beat`` completes a heartbeat under the deadline and
  returns rtt/peer stats; a hung heartbeat (the ``hang_peer_at_iter``
  fault — a peer that stops answering) blows the deadline and raises
  a structured ``PeerLostError`` instead of joining the stall.
- engine.train escalation: a hung peer mid-train checkpoints, flight-
  records the miss + ``peer_lost``, and exits ``EXIT_PREEMPTED`` (75)
  — after which a plain re-run resumes from the checkpoint to the
  bit-identical model (the elastic-resume handoff).
- ``from_config`` gating: the watchdog only exists (and only costs
  anything) when ``tpu_watchdog_deadline_s`` is set.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.obs.flightrec import global_flightrec, validate_dump
from lightgbm_tpu.obs.metrics import global_metrics
from lightgbm_tpu.resilience import faults as faults_mod
from lightgbm_tpu.resilience import watchdog as watchdog_mod
from lightgbm_tpu.resilience.errors import EXIT_PREEMPTED, PeerLostError
from lightgbm_tpu.resilience.watchdog import Watchdog


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults_mod.reset()
    global_flightrec.reset()


def _data(n=264, f=8, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * r.randn(n) > 0.4)
    return X, y.astype(np.float32)


class TestWatchdogUnit:
    def test_beat_completes_under_deadline(self):
        wd = Watchdog(deadline_s=30.0)
        out = wd.beat(0)
        assert out["ok"] and out["n_peers"] == 1
        assert out["rtt_s"] < 30.0
        assert wd.beats == 1 and wd.misses == 0
        st = wd.stats()
        assert st["beats"] == 1 and st["deadline_s"] == 30.0
        assert st["worst_rtt_s"] >= st["last_rtt_s"] >= 0.0

    def test_hung_heartbeat_raises_peer_lost(self):
        faults_mod.install(faults_mod.FaultPlan(
            hang_peer_at_iter=2, hang_peer_s=5.0))
        wd = Watchdog(deadline_s=0.15)
        wd.beat(0)
        wd.beat(1)
        before = global_metrics.counters.get(
            "resilience/watchdog_misses", 0)
        with pytest.raises(PeerLostError) as ei:
            wd.beat(2)
        assert ei.value.deadline_s == 0.15
        assert ei.value.iteration == 2
        assert ei.value.phase == "heartbeat"
        assert wd.misses == 1
        assert global_metrics.counters["resilience/watchdog_misses"] \
            == before + 1

    def test_miss_flight_records_and_dumps(self, tmp_path):
        dump = str(tmp_path / "wd.json")
        global_flightrec.enable(dump)
        faults_mod.install(faults_mod.FaultPlan(
            hang_peer_at_iter=0, hang_peer_s=5.0))
        wd = Watchdog(deadline_s=0.15)
        with pytest.raises(PeerLostError):
            wd.beat(0)
        assert os.path.exists(dump), "miss did not dump the black box"
        with open(dump) as fh:
            doc = json.load(fh)
        assert validate_dump(doc) == []
        assert doc["reason"] == "watchdog_heartbeat_miss"
        kinds = [e["kind"] for e in doc["events"]]
        assert "watchdog_heartbeat_miss" in kinds

    def test_closed_watchdog_stops_beating(self):
        wd = Watchdog(deadline_s=1.0)
        wd.close()
        out = wd.beat(0)
        assert out == {"ok": False, "closed": True}
        assert wd.beats == 0

    def test_from_config_gating(self):
        assert watchdog_mod.from_config(Config()) is None
        wd = watchdog_mod.from_config(
            Config.from_params({"tpu_watchdog_deadline_s": 2.5}))
        assert isinstance(wd, Watchdog) and wd.deadline_s == 2.5

    def test_rtt_feeds_stats_across_beats(self):
        wd = Watchdog(deadline_s=30.0)
        for i in range(3):
            wd.beat(i)
        assert wd.beats == 3
        assert wd.stats()["worst_rtt_s"] > 0.0


class TestEngineEscalation:
    def test_hung_peer_checkpoints_and_exits_75(self, tmp_path):
        """The full contract: hang at iteration k -> PeerLostError ->
        checkpoint + exit 75 -> plain re-run resumes to the
        bit-identical model."""
        X, y = _data()
        ck = str(tmp_path / "wd.ckpt")
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1, "tpu_checkpoint_path": ck,
                  "tpu_checkpoint_every": 2,
                  "tpu_watchdog_deadline_s": 0.3}
        straight = lgb.train(dict(params), lgb.Dataset(X, y),
                             num_boost_round=6).model_to_string()
        os.remove(ck)

        dump = str(tmp_path / "wd_dump.json")
        global_flightrec.enable(dump)
        faults_mod.install(faults_mod.FaultPlan(
            hang_peer_at_iter=3, hang_peer_s=5.0))
        with pytest.raises(SystemExit) as ei:
            lgb.train(dict(params), lgb.Dataset(X, y),
                      num_boost_round=6)
        assert ei.value.code == EXIT_PREEMPTED
        faults_mod.reset()
        assert os.path.exists(ck), "peer loss left no checkpoint"
        with open(dump) as fh:
            kinds = [e["kind"] for e in json.load(fh)["events"]]
        assert "watchdog_heartbeat_miss" in kinds
        assert "peer_lost" in kinds
        global_flightrec.reset()

        resumed = lgb.train(dict(params), lgb.Dataset(X, y),
                            num_boost_round=6).model_to_string()
        assert resumed == straight, \
            "post-peer-loss resume is not bit-identical"

    def test_no_watchdog_no_overhead_path(self):
        """Without the knob the engine never constructs a watchdog —
        the beats counter stays untouched."""
        before = global_metrics.counters.get(
            "resilience/watchdog_beats", 0)
        X, y = _data()
        lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, y),
                  num_boost_round=3)
        assert global_metrics.counters.get(
            "resilience/watchdog_beats", 0) == before

    def test_watchdog_on_clean_run_is_silent(self, tmp_path):
        """With the knob but no fault: beats accrue, no misses, the
        model is bit-identical to an unwatched run."""
        X, y = _data()
        params = {"objective": "binary", "num_leaves": 7,
                  "verbosity": -1}
        plain = lgb.train(dict(params), lgb.Dataset(X, y),
                          num_boost_round=4).predict(X)
        before = global_metrics.counters.get(
            "resilience/watchdog_misses", 0)
        watched = lgb.train(
            dict(params, tpu_watchdog_deadline_s=30.0),
            lgb.Dataset(X, y), num_boost_round=4).predict(X)
        assert np.array_equal(np.asarray(watched), np.asarray(plain))
        assert global_metrics.counters.get(
            "resilience/watchdog_misses", 0) == before
